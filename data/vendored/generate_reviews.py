"""Generator for the vendored sentiment corpus (`train.jsonl`/`test.jsonl`).

PROVENANCE: this environment is zero-egress — the IMDb dataset the
reference trains on (reference ``scripts/train.py:72``) is unreachable,
and no labeled corpus ships with the image. This corpus is therefore
AUTHORED IN-REPO: every sentence below was written by hand for this
file; reviews are seeded, deterministic compositions of those sentences.
It is natural English with the failure modes real sentiment data has
(negation, concession, mixed opinions, shared vocabulary across
classes) — but it is NOT IMDb and accuracy on it is not an IMDb number.
When the HF hub is reachable, `--dataset imdb` runs the real thing.

Hard-case design (what keeps a keyword counter from acing it):
- negated cues: "not great", "never boring", "couldn't call it a failure"
  appear with BOTH labels' vocabulary;
- concessive reviews (~45% of rows, MIXED_RATE): minority- and
  dominant-polarity clauses in EQUAL number, the label decided only by
  which clause follows the joiner ("the effects are shoddy, yet the
  story lands");
- neutral filler sentences shared verbatim across classes;
- the same nouns/slots (acting, script, pacing, score, ending...) fill
  both positive and negative frames.

Regenerate with:  python data/vendored/generate_reviews.py
"""

from __future__ import annotations

import json
import os
import random

# --- hand-authored sentence banks -----------------------------------------

SLOTS = {
    "aspect": [
        "the acting", "the script", "the pacing", "the cinematography",
        "the score", "the dialogue", "the ending", "the direction",
        "the casting", "the editing", "the premise", "the soundtrack",
        "the lead performance", "the supporting cast", "the final act",
        "the opening sequence", "the character work", "the camera work",
        "the production design", "the humor",
    ],
    "person": [
        "the director", "the lead actor", "the lead actress",
        "the screenwriter", "the composer", "the whole cast",
        "the cinematographer", "the editor",
    ],
    "genre": [
        "thriller", "drama", "comedy", "romance", "mystery", "western",
        "horror picture", "war film", "character study", "family film",
        "courtroom drama", "road movie", "heist picture", "biopic",
    ],
    "time": [
        "two hours", "an entire afternoon", "a rainy Sunday",
        "the whole runtime", "ninety minutes",
    ],
}

POS_FRAMES = [
    "{aspect} is simply outstanding",
    "{aspect} carries the whole picture",
    "{aspect} had me hooked from the first minute",
    "{aspect} deserves every award it can get",
    "{aspect} is handled with real care and intelligence",
    "{aspect} builds to something genuinely moving",
    "{aspect} is the best I have seen in years",
    "{aspect} crackles with wit and energy",
    "{aspect} rewards your full attention",
    "{aspect} is quietly devastating in the best way",
    "{aspect} never puts a foot wrong",
    "{aspect} elevates familiar material into something special",
    "{person} delivers career-best work here",
    "{person} clearly poured heart and soul into this",
    "{person} finds grace notes in every scene",
    "{person} makes brave choices that pay off beautifully",
    "i was moved to tears more than once",
    "i left the theater grinning like an idiot",
    "i cannot remember the last time a {genre} felt this alive",
    "this is the rare {genre} that trusts its audience",
    "every frame feels purposeful and alive",
    "it earns its emotional climax honestly",
    "the twists land because the characters are real",
    "scene after scene lands with surprising force",
    "it is funny, tender, and wise all at once",
    "a masterpiece, plain and simple",
    "an absolute triumph from start to finish",
    "you will want to watch it twice, immediately",
    "it repays {time} with interest",
    "easily the highlight of the season, and it is not close",
    "the film finds something true about ordinary life",
    "even the small roles are cast to perfection",
    "the climax is staged with breathtaking confidence",
    "it balances humor and heartbreak effortlessly",
    "this one stays with you for days",
]

NEG_FRAMES = [
    "{aspect} is an outright disaster",
    "{aspect} drags the whole picture down",
    "{aspect} put me to sleep twice",
    "{aspect} feels phoned in from another, worse movie",
    "{aspect} is handled with stunning carelessness",
    "{aspect} builds to absolutely nothing",
    "{aspect} is the weakest element by far",
    "{aspect} lands with a dull thud",
    "{aspect} insults the audience's patience",
    "{aspect} collapses under the slightest scrutiny",
    "{aspect} never rises above tired cliche",
    "{aspect} squanders a promising setup",
    "{person} sleepwalks through the entire film",
    "{person} has never seemed so lost",
    "{person} mistakes volume for emotion",
    "{person} makes baffling choices that never pay off",
    "i checked my watch every ten minutes",
    "i walked out feeling cheated",
    "i cannot remember a {genre} this inert",
    "this is the kind of {genre} that gives the genre a bad name",
    "every frame feels recycled and tired",
    "it begs for an emotional response it never earns",
    "the twists are visible from a mile away",
    "scene after scene lands with a thud",
    "it is loud, shallow, and endless",
    "a mess, plain and simple",
    "an absolute slog from start to finish",
    "you will want those {time} back",
    "it wastes {time} and your goodwill",
    "easily the low point of the season, and it is not close",
    "the film has nothing to say and takes forever to say it",
    "even the small roles are miscast",
    "the climax is staged with baffling clumsiness",
    "it mistakes misery for depth",
    "this one evaporates from memory before the credits end",
]

# negation flips: positive-label sentences built from "bad" vocabulary and
# vice versa — a bag-of-words model pays for these
POS_NEGATED = [
    "it is never boring, not even for a second",
    "nothing about it feels fake or forced",
    "i expected a disaster and could not have been more wrong",
    "this is not the tired {genre} the trailer promised",
    "there is not a wasted scene anywhere",
    "nobody phones it in, least of all {person}",
    "it never drags, despite the long runtime",
    "you could not call a single performance weak",
    "far from a mess, it is meticulously constructed",
    "i kept waiting for it to fall apart, and it never did",
]

NEG_NEGATED = [
    "it is never exciting, not even for a second",
    "nothing about it feels honest or earned",
    "i expected a masterpiece and could not have been more wrong",
    "this is not the smart {genre} the reviews promised",
    "there is not a memorable scene anywhere",
    "nobody brings any spark, least of all {person}",
    "it never builds momentum, despite the frantic editing",
    "you could not call a single performance convincing",
    "far from a triumph, it is barely coherent",
    "i kept waiting for it to come alive, and it never did",
]

NEUTRAL = [
    "i saw this at a matinee with maybe ten other people",
    "the film runs just over {time}",
    "it is based, loosely, on true events",
    "this is the director's third feature",
    "the trailer gives away more than it should",
    "i went in knowing almost nothing about it",
    "it opened against much bigger releases",
    "the screening i attended was nearly sold out",
    "my expectations were set mostly by word of mouth",
    "it follows the usual beats of a {genre}",
    "the cast is a mix of veterans and newcomers",
    "there is a brief scene after the credits",
    "i watched it again at home a week later",
    "the setting shifts between two timelines",
    "much of it was shot on location",
]

CONCESSION_JOINERS = ["that said,", "even so,", "still,", "and yet,",
                      "in the end though,", "but"]


def _fill(rng: random.Random, frame: str) -> str:
    out = frame
    for slot, options in SLOTS.items():
        while "{" + slot + "}" in out:
            out = out.replace("{" + slot + "}", rng.choice(options), 1)
    return out


def _sentence(rng, bank):
    return _fill(rng, rng.choice(bank))


MIXED_RATE = 0.45


def make_review(rng: random.Random, label: int) -> str:
    """Two review shapes:

    - ~45% "mixed": 1-2 concession units, each a minority-polarity clause
      rebutted by a dominant one after a concessive joiner ("the pacing
      drags. even so, the ending lands"). Both polarities contribute the
      SAME number of opinion clauses, so bag-of-words carries no signal —
      the label rides entirely on which clause follows the joiner.
    - else "clear": 2-4 dominant sentences (~35% of them negated
      minority-vocabulary frames, blurring the exclusive-word signal),
      plus neutral filler.
    """
    main = POS_FRAMES if label == 1 else NEG_FRAMES
    main_neg = POS_NEGATED if label == 1 else NEG_NEGATED
    other = NEG_FRAMES if label == 1 else POS_FRAMES

    sentences = []
    if rng.random() < MIXED_RATE:
        for _ in range(rng.randint(1, 2)):
            concession = _sentence(rng, other)
            joiner = rng.choice(CONCESSION_JOINERS)
            rebuttal = _sentence(rng, main)
            sentences.append(f"{concession}. {joiner} {rebuttal}")
    else:
        for _ in range(rng.randint(2, 4)):
            bank = main_neg if rng.random() < 0.35 else main
            sentences.append(_sentence(rng, bank))
    for _ in range(rng.randint(0, 3)):
        sentences.append(_sentence(rng, NEUTRAL))  # shuffle places them
    rng.shuffle(sentences)
    text = ". ".join(s.rstrip(".") for s in sentences) + "."
    return text[0].upper() + text[1:]


def generate(n_train: int = 4000, n_test: int = 1000, seed: int = 0) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    out_dir = os.path.join(here, "reviews")
    os.makedirs(out_dir, exist_ok=True)
    for split, n, split_seed in (("train", n_train, seed),
                                 ("test", n_test, seed + 1)):
        rng = random.Random(split_seed)
        with open(os.path.join(out_dir, f"{split}.jsonl"), "w") as f:
            for i in range(n):
                label = i % 2
                f.write(json.dumps({"text": make_review(rng, label),
                                    "label": label}) + "\n")
    print(f"wrote {n_train}+{n_test} reviews to {out_dir}")


if __name__ == "__main__":
    generate()
