"""Serving entry point: drive the continuous-batching engine
(``serve/engine.py``) over a request trace and report per-request
latency + aggregate throughput.

Requests come from ``--input_file`` (JSONL, one
``{"prompt_ids": [...], "max_new_tokens": N}`` per line, optionally
carrying per-request ``temperature``/``top_k``/``top_p``/``seed``) or a
synthetic mixed-length trace (default — the zero-egress smoke path).
``--temperature/--top_k/--top_p/--sample_seed`` set the default
sampling configuration (greedy when temperature is 0);
``--gather_buckets`` overrides the decode gather-width ladder
(``HSTD_SERVE_GATHER_BUCKETS``; ``full`` disables bucketing);
``--prefix_cache on|off`` (``HSTD_SERVE_PREFIX_CACHE``, default on)
controls copy-on-write prompt-prefix KV sharing — per-request output
rows carry ``prefix_cached_tokens`` and the summary line the aggregate
cache hit rate + peak shared-block count. The model is
a randomly-initialized GPT-2 shape by default (``--model_dir`` loads an
exported causal-lm checkpoint the way ``scripts/predict.py`` does).

  # synthetic trace on the smoke model, engine knobs explicit
  python scripts/serve.py --requests 32 --num_slots 8 --block_size 16 \
      --prefill_chunk 16

  # real checkpoint
  python scripts/serve.py --model_dir /path/to/export \
      --input_file requests.jsonl

One JSON line per finished request (ids, TTFT, decode tokens/sec), then
one summary line (aggregate tokens/sec, TTFT percentiles, KV-pool peak
utilization, preemptions). With ``HSTD_TELEMETRY_DIR`` set, the engine
additionally streams ``serve`` lifecycle events + spans through ``obs``.
``--timeline on`` (``HSTD_SERVE_TIMELINE``, default on) adds
per-request lifecycle tracing: each output row carries its phase
decomposition (queue/prefill/decode/preempted seconds), the summary the
run-wide phase fractions + queue-wait p99, and the telemetry stream the
``request_timeline``/``iteration_ledger`` events that ``obsctl
timeline|slo|tail`` consume. ``--tp N`` (``HSTD_SERVE_TP``, default 1)
serves TENSOR-PARALLEL: params + KV pools shard over N devices (pools
on their heads axis — ``num_kv_heads % N == 0`` required), output
stays token-identical to the single-device engine, and the per-device
KV byte budget buys ~N× the resident requests; rows and the summary
carry ``tp``, the summary additionally ``kv_pool_bytes_per_device``.

``--replicas N --placement round_robin|least_loaded|affinity``
(``HSTD_SERVE_REPLICAS`` / ``HSTD_SERVE_PLACEMENT``, default
1/round_robin) serves MULTI-REPLICA (ISSUE 14): N engine replicas —
each its own scheduler/pool/prefix cache — behind one router with SLO-
and prefix-affinity-aware placement. Output is token-identical to a
single-engine run under every policy (placement cannot change tokens);
with N > 1 each per-request row carries its ``replica`` and the
summary the fleet view (``placement``, ``replica_load_imbalance``,
per-replica hit-rate/depth aggregates). ``--replicas 1`` is the
byte-identical single-engine path, telemetry included.

``--arrival poisson:RATE|bursty:HI,LO,P|closed`` (``HSTD_SERVE_ARRIVAL``
+ ``HSTD_SERVE_ARRIVAL_SEED``, default closed) serves OPEN-LOOP
(ISSUE 16): the trace arrives on a seeded schedule through
``serve/loadgen.py``'s wall-clock driver instead of all at once, so
offered load no longer self-throttles on engine backpressure.
``--slo ttft:SECS[,tpot:SECS]`` (``HSTD_SERVE_SLO_TTFT_S`` /
``HSTD_SERVE_SLO_TPOT_S``) attaches per-request deadlines — each
output row then carries ``slo_met``/``slack_s`` and the summary the
run's ``slo_attainment``, goodput tokens, per-group split and
dominant miss phase (the figures ``obsctl goodput`` recomputes from
the telemetry stream). ``--slo`` without ``--arrival`` judges the
closed-loop trace from submit time.

``--roles prefill:N,decode:M`` (``HSTD_SERVE_ROLES``, default off)
serves DISAGGREGATED (ISSUE 18): N prefill-only replicas run chunked
prefill at the full token budget and hand each finished request's live
KV block set to the least-loaded decode replica over
``serve/transport.py`` — zero re-prefill, token-identical output. The
summary gains ``roles``, ``migrations``/``migration_bytes`` and a
``per_role`` breakdown (prefill-side TTFT percentiles, decode-side
TPOT percentiles + tokens/sec). Requires ``--replicas`` unset or equal
to N+M. The same transport powers ``Router.drain``: draining a replica
now live-migrates its RESIDENT requests to siblings mid-decode instead
of waiting them out, so rolling restarts are preemption-free.

``--swap auto|always|never|off`` (``HSTD_SERVE_SWAP``, default off)
turns on the host-RAM KV spill tier (ISSUE 17): preemption victims
swap their KV block sets to host and restore on re-admit without
re-prefill (``auto`` picks swap vs recompute per victim from the
bytes-moved vs weight-traffic estimate), and zero-ref prefix-cache
blocks demote to host before true eviction, reviving on match.
``--swap_bytes N`` (``HSTD_SERVE_SWAP_BYTES``, 0 = unbounded) caps the
host tier. With the tier on, the summary carries ``swap_policy``,
swap traffic (``swap_outs``/``swap_ins``/``swap_bytes``/``restore_s``),
``recompute_tokens_avoided`` and the demote tier's
``host_tier_hits``/``host_tier_hit_rate``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def load_model(args):
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    if args.model_dir:
        from huggingface_sagemaker_tensorflow_distributed_tpu.models import (
            auto as auto_models,
        )
        model, params, _family, _config = auto_models.from_pretrained(
            args.model_dir, task="causal-lm")
        return model, params
    cfg = Gpt2Config(vocab_size=1024, hidden_size=256, num_layers=4,
                     num_heads=4, intermediate_size=1024,
                     max_position_embeddings=512, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=1023, pad_token_id=0,
                     dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return model, init_params(model, cfg, seed=0)


def _sampling_kw(row, defaults, where: str) -> dict:
    """Per-request sampling fields from one JSONL row, validated
    LOUDLY: a drifted trace (bool/string/fractional top_k) must name
    its line, not silently serve different truncation than specified.
    JSON null (and absence) mean "use the CLI default"."""
    kw = {}
    for k, default in defaults.items():
        raw = row.get(k)
        if raw is None:
            kw[k] = default
            continue
        if isinstance(raw, bool) or not isinstance(raw, (int, float)):
            raise SystemExit(
                f"serve: {where}: field {k!r} must be a number, "
                f"got {raw!r}")
        if isinstance(default, int) and raw != int(raw):
            raise SystemExit(
                f"serve: {where}: field {k!r} must be an integer, "
                f"got {raw!r}")
        kw[k] = type(default)(raw)
    return kw


def load_trace(args, vocab: int):
    """[(prompt_ids, max_new_tokens, sampling_kwargs)] — per-request
    JSONL fields override the CLI-wide sampling defaults."""
    defaults = {"temperature": args.temperature, "top_k": args.top_k,
                "top_p": args.top_p, "seed": args.sample_seed}
    if args.input_file:
        trace = []
        with open(args.input_file, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                if not line.strip():
                    continue
                row = json.loads(line)
                kw = _sampling_kw(row, defaults,
                                  f"{args.input_file}:{lineno}")
                # graftlint: allow[R2] host-side JSONL decode before the engine exists — nothing device-resident to block on
                trace.append((np.asarray(row["prompt_ids"], np.int32),
                              int(row.get("max_new_tokens",
                                          args.max_new_tokens)), kw))
        return trace
    from benchmarks.serve_bench import make_trace

    rng = np.random.RandomState(args.seed)
    base = make_trace(rng, args.requests, vocab, args.prompt_min,
                      args.prompt_max, (4, max(4, args.max_new_tokens // 4)),
                      (args.max_new_tokens // 2, args.max_new_tokens),
                      long_every=4)
    return [(p, m, dict(defaults)) for p, m in base]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model_dir", default=None)
    parser.add_argument("--input_file", default=None,
                        help="JSONL of {prompt_ids, max_new_tokens}")
    parser.add_argument("--requests", type=int, default=32,
                        help="synthetic-trace request count")
    parser.add_argument("--prompt_min", type=int, default=8)
    parser.add_argument("--prompt_max", type=int, default=48)
    parser.add_argument("--max_new_tokens", type=int, default=64)
    parser.add_argument("--num_slots", type=int, default=8)
    parser.add_argument("--block_size", type=int, default=16)
    parser.add_argument("--num_blocks", type=int, default=0,
                        help="KV pool blocks incl. the null block "
                             "(0 = 3/4 of slots * max_model_len)")
    parser.add_argument("--prefill_chunk", type=int, default=16)
    parser.add_argument("--prefill_batch", type=int, default=4,
                        help="max prefilling slots packed per dispatch")
    parser.add_argument("--max_model_len", type=int, default=0,
                        help="0 = model max_position_embeddings")
    parser.add_argument("--gather_buckets", default=None,
                        help="decode gather-width ladder, e.g. "
                             "'64,256' ('full' disables bucketing; "
                             "default: HSTD_SERVE_GATHER_BUCKETS or "
                             "quarter+full width)")
    parser.add_argument("--speculate_k", type=int, default=None,
                        help="speculative decode: draft tokens per "
                             "verify window (default: "
                             "HSTD_SERVE_SPECULATE_K or 0 = off)")
    parser.add_argument("--draft_layers", type=int, default=None,
                        help="layer-skip self-draft depth (default: "
                             "HSTD_SERVE_DRAFT_LAYERS or a quarter of "
                             "the target's layers)")
    parser.add_argument("--prefix_cache", default=None,
                        choices=("on", "off"),
                        help="copy-on-write prompt-prefix KV sharing "
                             "across requests (default: "
                             "HSTD_SERVE_PREFIX_CACHE or on)")
    parser.add_argument("--kernel", default=None,
                        choices=("xla", "pallas"),
                        help="decode attention path: xla = gather + "
                             "dense (reference), pallas = fused paged "
                             "kernel (interpret mode off-TPU; default: "
                             "HSTD_SERVE_KERNEL or xla)")
    parser.add_argument("--kv_cache_dtype", default=None,
                        choices=("fp", "int8"),
                        help="KV pool storage; int8 halves pool bytes "
                             "per decode step (default: "
                             "HSTD_SERVE_KV_DTYPE or the model config)")
    parser.add_argument("--timeline", default=None,
                        choices=("on", "off"),
                        help="per-request lifecycle tracing "
                             "(request_timeline/iteration_ledger "
                             "events + phase decomposition in the "
                             "summary; default: HSTD_SERVE_TIMELINE "
                             "or on)")
    parser.add_argument("--tp", type=int, default=None,
                        help="tensor-parallel degree: shard params + "
                             "KV pools (heads axis) over this many "
                             "devices so one engine serves models "
                             "bigger than a chip; num_kv_heads must "
                             "divide (rejected loudly otherwise) and "
                             "the KV byte budget re-denominates per "
                             "device (default: HSTD_SERVE_TP or 1)")
    parser.add_argument("--replicas", type=int, default=None,
                        help="multi-replica serving: engine replicas "
                             "behind the placement router; each "
                             "replica owns its scheduler/KV pool/"
                             "prefix cache, output stays token-"
                             "identical to one engine (default: "
                             "HSTD_SERVE_REPLICAS or 1 = the byte-"
                             "identical single-engine path)")
    parser.add_argument("--placement", default=None,
                        choices=("round_robin", "least_loaded",
                                 "affinity"),
                        help="replica placement policy: round_robin, "
                             "least_loaded (live waiting-depth + KV-"
                             "pressure gauges), or affinity (route to "
                             "the replica holding the longest cached "
                             "prefix, imbalance-bounded; default: "
                             "HSTD_SERVE_PLACEMENT or round_robin)")
    parser.add_argument("--roles", default=None,
                        help="disaggregated prefill/decode fleet, "
                             "prefill:N,decode:M — prefill-only "
                             "replicas hand finished KV block sets to "
                             "decode replicas over the transport "
                             "primitive, token-identically (default: "
                             "HSTD_SERVE_ROLES or off = mixed "
                             "replicas)")
    parser.add_argument("--overlap", default=None,
                        choices=("on", "off"),
                        help="dispatch-ahead decode loop: host "
                             "scheduling overlaps the in-flight "
                             "device step, device_get deferred one "
                             "iteration; off restores the serial "
                             "loop byte-for-byte (default: "
                             "HSTD_SERVE_OVERLAP or on)")
    parser.add_argument("--arrival", default=None,
                        help="open-loop arrival process: poisson:RATE "
                             "(req/s), bursty:RATE_HI,RATE_LO,P_SWITCH "
                             "(Markov-modulated), or closed = submit "
                             "the whole trace up front (default: "
                             "HSTD_SERVE_ARRIVAL or closed; schedule "
                             "seed: HSTD_SERVE_ARRIVAL_SEED)")
    parser.add_argument("--slo", default=None,
                        help="per-request deadline targets, "
                             "ttft:SECS[,tpot:SECS] or none: rows gain "
                             "slo_met/slack_s, the summary "
                             "slo_attainment + miss attribution "
                             "(default: HSTD_SERVE_SLO_TTFT_S / "
                             "HSTD_SERVE_SLO_TPOT_S)")
    parser.add_argument("--policy", default=None,
                        choices=("fifo", "slo"),
                        help="admission-ordering policy: fifo = strict "
                             "arrival order, slo = earliest effective "
                             "deadline folding in priority class, "
                             "predicted demand (prefix-cache aware) "
                             "and a bounded aging term (default: "
                             "HSTD_SERVE_POLICY or fifo)")
    parser.add_argument("--aging_s", type=float, default=None,
                        help="starvation bound for --policy slo: a "
                             "request waiting this long is promoted "
                             "ahead of all unpromoted work (default: "
                             "HSTD_SERVE_AGING_S or 30)")
    parser.add_argument("--rate_limit", default=None,
                        help="per-tenant token-bucket admission caps, "
                             "GROUP=RATE[:BURST],... req/s keyed on "
                             "each request's group tag ('*' = default "
                             "bucket); over-budget submits get a "
                             "structured rate_limited rejection, "
                             "never a silent drop")
    parser.add_argument("--swap", default=None,
                        choices=("auto", "always", "never", "off"),
                        help="host-RAM KV spill tier: swap preemption "
                             "victims to host + demote evicted prefix "
                             "blocks (auto = per-victim bytes-vs-"
                             "recompute estimate; never = demotion "
                             "only; default: HSTD_SERVE_SWAP or off)")
    parser.add_argument("--swap_bytes", type=int, default=None,
                        help="host-tier byte budget shared by demoted "
                             "payloads and swap reservations "
                             "(default: HSTD_SERVE_SWAP_BYTES or "
                             "0 = unbounded)")
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="0 = greedy (the default); > 0 samples")
    parser.add_argument("--top_k", type=int, default=0)
    parser.add_argument("--top_p", type=float, default=0.0)
    parser.add_argument("--sample_seed", type=int, default=0,
                        help="per-request sampling seed default")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    from huggingface_sagemaker_tensorflow_distributed_tpu import obs
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.loadgen import (
        OpenLoopDriver,
        bursty_arrivals,
        parse_arrival,
        parse_arrival_seed,
        parse_slo,
        poisson_arrivals,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
        Router,
        parse_roles,
    )

    try:
        arrival = parse_arrival(args.arrival)
        arrival_seed = parse_arrival_seed()
        slo_spec = parse_slo(args.slo)
        roles = parse_roles(args.roles)
    except ValueError as e:
        raise SystemExit(f"serve: {e}")

    obs.configure()
    model, params = load_model(args)
    max_len = args.max_model_len or (
        model.config.max_position_embeddings
        // args.block_size) * args.block_size
    num_blocks = args.num_blocks or (
        1 + args.num_slots * (max_len // args.block_size) * 3 // 4)
    # the router is the one construction path: replicas=1 (the
    # default) is a pass-through whose engine behavior AND telemetry
    # stream are byte-identical to building the ServeEngine directly
    router = Router(model, params, replicas=args.replicas,
                    placement=args.placement, roles=roles,
                    num_slots=args.num_slots,
                    block_size=args.block_size, num_blocks=num_blocks,
                    prefill_chunk=args.prefill_chunk,
                    prefill_batch=args.prefill_batch,
                    max_model_len=max_len,
                    gather_buckets=args.gather_buckets,
                    speculate_k=args.speculate_k,
                    draft=args.draft_layers,
                    prefix_cache=args.prefix_cache,
                    kernel=args.kernel,
                    kv_cache_dtype=args.kv_cache_dtype,
                    timeline=args.timeline,
                    overlap=args.overlap,
                    mesh=args.tp,
                    swap=args.swap,
                    swap_bytes=args.swap_bytes,
                    policy=args.policy,
                    aging_s=args.aging_s,
                    rate_limit=args.rate_limit)
    engine = router.engines[0]
    trace = load_trace(args, model.config.vocab_size - 1)
    # precompile the sampled step variants too when the trace will
    # sample, so no request pays a mid-serve compile
    router.warmup(sampled=any(kw.get("temperature", 0) > 0
                              for _, _, kw in trace))
    driver = None
    if arrival is not None:
        # open loop: the trace arrives on the seeded schedule through
        # the wall-clock driver — arrival_s + the SLO thread into
        # submit, so the engine stamps real verdicts into telemetry
        proc, pp = arrival
        if proc == "poisson":
            arrivals = poisson_arrivals(pp["rate"], len(trace),
                                        arrival_seed)
            rate = pp["rate"]
        else:
            arrivals = bursty_arrivals(pp["rate_hi"], pp["rate_lo"],
                                       pp["p_switch"], len(trace),
                                       arrival_seed)
            rate = pp["rate_hi"]
        schedule = [
            (a, {"prompt": p, "max_new_tokens": m, **kw})
            for a, (p, m, kw) in zip(arrivals, trace)]
        driver = OpenLoopDriver(router, schedule, clock="wall",
                                slo=slo_spec, process=proc, rate=rate)
        t0 = time.perf_counter()
        finished = driver.run()
        wall = time.perf_counter() - t0
        reqs = [finished[rid] for rid in sorted(finished)]
    else:
        reqs, rejected = [], 0
        for p, m, kw in trace:
            r = router.submit(p, m, slo=slo_spec, **kw)
            if getattr(r, "rejected", False):
                rejected += 1
            else:
                reqs.append(r)
        t0 = time.perf_counter()
        router.run()
        wall = time.perf_counter() - t0

    total = 0
    for req in reqs:
        ids = router.output_ids(req)
        total += len(ids)
        row = {
            "request": req.rid, "prompt_len": req.orig_prompt_len,
            "output_ids": [int(t) for t in ids],
            "ttft_s": round(req.ttft_s, 4) if req.ttft_s else None,
            "sampled": req.sampled, "seed": req.seed,
            "preemptions": req.preemptions, "tp": engine.tp}
        if req.has_slo:
            # the engine's own verdict (stamped at finish): deadline
            # met, and the worst axis's margin in seconds
            row["slo_met"] = req.slo_met
            row["slack_s"] = req.slack_s
        if req.deadline_s is not None:
            row["deadline_s"] = req.deadline_s
            row["deadline_miss"] = req.deadline_miss
        if req.priority:
            row["priority"] = req.priority
        if router.n > 1:
            row["replica"] = router.replica_of(req)
        if engine.speculative:
            row["acceptance_rate"] = (
                round(req.spec_accepted / req.spec_proposed, 4)
                if req.spec_proposed else None)
        if engine.prefix_cache:
            row["prefix_cached_tokens"] = req.prefix_cached_tokens
        if engine.timeline:
            # the request's own phase decomposition (what its
            # request_timeline telemetry event carries in full)
            row["phase_s"] = {ph: round(v, 4)
                              for ph, v in req.phase_s.items()}
        print(json.dumps(row))
    # open-loop / SLO summary fields (absent on a plain closed run):
    # the driver's goodput accounting — the same figures `obsctl
    # goodput` recomputes offline from the telemetry stream
    open_extra = {}
    if slo_spec is not None:
        open_extra["slo"] = {"ttft_s": slo_spec.ttft_s,
                             "tpot_s": slo_spec.tpot_s}
    if driver is not None:
        dsum = driver.summary()
        open_extra["arrival"] = {"process": dsum["process"],
                                 "rate": dsum.get("rate"),
                                 "seed": arrival_seed,
                                 "clock": dsum["clock"]}
        for k in ("slo_attainment", "slo_met", "slo_missed",
                  "goodput_tokens", "group_slo_attainment",
                  "miss_phases", "dominant_miss_phase",
                  "rate_limited", "deadline_misses",
                  "deadline_miss_frac"):
            if k in dsum:
                open_extra[k] = dsum[k]
    elif rejected:
        open_extra["rate_limited"] = rejected
    if router.n > 1:
        # fleet summary (ISSUE 14): the router's own aggregate (the
        # same figures its final `serve` report telemetry event
        # carries) plus summed engine counters — per-replica hit-rate/
        # depth aggregates ride `per_replica`
        rslo = router.slo_summary()
        stats_all = [e.stats() for e in router.engines]
        print(json.dumps({
            "summary": True,
            "requests": len(reqs),
            "tokens": total,
            "tokens_per_sec": round(total / wall, 1),
            "replicas": router.n,
            "placement": router.placement,
            "drains": router.drains,
            "requeues": router.requeues,
            "replica_load_imbalance": rslo.get("replica_load_imbalance"),
            "affinity_fallbacks": (router.affinity_fallbacks
                                   if router.placement == "affinity"
                                   else None),
            "ttft_p50_s": rslo.get("ttft_p50_s"),
            "ttft_p95_s": rslo.get("ttft_p95_s"),
            "ttft_p99_s": rslo.get("ttft_p99_s"),
            "e2e_p50_s": rslo.get("e2e_p50_s"),
            "e2e_p95_s": rslo.get("e2e_p95_s"),
            "e2e_p99_s": rslo.get("e2e_p99_s"),
            "peak_waiting_depth": rslo.get("peak_waiting_depth"),
            "decode_steps": sum(s.decode_steps for s in stats_all),
            "decode_tokens_per_sec": rslo.get("decode_tokens_per_sec"),
            "prefill_chunks": sum(s.prefill_chunks for s in stats_all),
            "preemptions": sum(s.preemptions for s in stats_all),
            "gather_buckets": engine.gather_buckets,
            "prefix_cache": engine.prefix_cache,
            "cache_hit_rate": rslo.get("cache_hit_rate"),
            "timeline": engine.timeline,
            "overlap": engine.overlap,
            "kernel": engine.kernel,
            "kv_dtype": engine.kv_cache_dtype,
            "tp": engine.tp,
            "per_replica": rslo.get("per_replica"),
            **({"roles": rslo.get("roles"),
                "per_role": rslo.get("per_role"),
                "migrations": router.migrations,
                "migration_bytes": sum(
                    s.migration_bytes for s in stats_all)}
               if router.roles is not None else {}),
            **({"swap_policy": engine.swap,
                "swap_outs": sum(s.swap_outs for s in stats_all),
                "swap_ins": sum(s.swap_ins for s in stats_all),
                "swap_bytes": sum(s.swap_bytes for s in stats_all),
                "recompute_tokens_avoided": sum(
                    s.recompute_tokens_avoided for s in stats_all),
                "host_tier_hits": sum(
                    s.host_tier_hits for s in stats_all)}
               if engine.swap != "off" else {}),
            **({"arrival_backlog_peak":
                rslo.get("arrival_backlog_peak")}
               if driver is not None else {}),
            **({"slo_attainment": rslo.get("slo_attainment"),
                "group_slo_attainment":
                rslo.get("group_slo_attainment")}
               if slo_spec is not None and driver is None else {}),
            **({"policy": router.policy,
                "aging_promotions": rslo.get("aging_promotions")}
               if router.policy != "fifo" else {}),
            **({"deadline_miss_frac": rslo.get("deadline_miss_frac")}
               if rslo.get("deadline_miss_frac") is not None else {}),
            **({"priority_slo_attainment":
                rslo.get("priority_slo_attainment")}
               if rslo.get("priority_slo_attainment") else {}),
            **open_extra}))
        obs.flush()
        return
    stats = engine.stats()
    # SLO summary from the engine's own accounting (the same figures
    # its final `serve` report telemetry event carries): TTFT + e2e
    # latency percentiles and scheduler gauges
    slo = engine.slo_summary()
    print(json.dumps({
        "summary": True,
        "requests": len(reqs),
        "tokens": total,
        "tokens_per_sec": round(total / wall, 1),
        "ttft_p50_s": slo.get("ttft_p50_s"),
        "ttft_p95_s": slo.get("ttft_p95_s"),
        "ttft_p99_s": slo.get("ttft_p99_s"),
        "e2e_p50_s": slo.get("e2e_p50_s"),
        "e2e_p95_s": slo.get("e2e_p95_s"),
        "e2e_p99_s": slo.get("e2e_p99_s"),
        "peak_waiting_depth": slo.get("peak_waiting_depth"),
        "decode_steps": stats.decode_steps,
        "decode_tokens_per_sec": round(
            stats.decode_tokens / stats.decode_time_s, 1)
        if stats.decode_time_s > 0 else None,
        "prefill_chunks": stats.prefill_chunks,
        "prefill_dispatches": stats.prefill_dispatches,
        "preemptions": stats.preemptions,
        "gather_buckets": engine.gather_buckets,
        "bucket_switches": stats.bucket_switches,
        "gather_read_waste_peak": round(stats.gather_waste_peak, 3),
        "gather_read_waste_mean": round(stats.gather_waste_mean, 3),
        "speculate_k": engine.speculate_k or None,
        "acceptance_rate": (round(stats.acceptance_rate, 4)
                            if stats.acceptance_rate is not None else None),
        "verify_read_waste_mean": (round(stats.verify_waste_mean, 3)
                                   if engine.speculative else None),
        "prefix_cache": engine.prefix_cache,
        "cache_hit_rate": (round(stats.cache_hit_rate, 4)
                           if stats.cache_hit_rate is not None else None),
        "blocks_shared_peak": (stats.blocks_shared_peak
                               if engine.prefix_cache else None),
        "blocks_saved_peak": (stats.blocks_saved_peak
                              if engine.prefix_cache else None),
        "cow_copies": stats.cow_copies if engine.prefix_cache else None,
        "timeline": engine.timeline,
        "queue_wait_p99_s": slo.get("queue_wait_p99_s"),
        "queue_time_frac": slo.get("queue_time_frac"),
        "prefill_time_frac": slo.get("prefill_time_frac"),
        "decode_time_frac": slo.get("decode_time_frac"),
        "preempted_time_frac": slo.get("preempted_time_frac"),
        "overhead_time_frac": slo.get("overhead_time_frac"),
        "overlap": engine.overlap,
        "overlap_flushes": (stats.overlap_flushes
                            if engine.overlap else None),
        "kernel": stats.kernel,
        "kv_dtype": stats.kv_dtype,
        "tp": stats.tp,
        "kv_pool_bytes_per_device": stats.kv_pool_bytes_per_device or None,
        "kv_bytes_read_per_step": (round(
            stats.kv_bytes_read / stats.decode_steps, 1)
            if stats.decode_steps else None),
        "kv_peak_utilization": round(stats.kv_peak_utilization, 3),
        **({"swap_policy": stats.swap_policy,
            "swap_outs": stats.swap_outs,
            "swap_ins": stats.swap_ins,
            "swap_bytes": stats.swap_bytes,
            "restore_s": round(stats.restore_s, 6),
            "recompute_tokens_avoided": stats.recompute_tokens_avoided,
            "host_tier_hits": stats.host_tier_hits,
            "host_tier_hit_rate": (
                round(stats.host_tier_hit_rate, 4)
                if stats.host_tier_hit_rate is not None else None)}
           if engine.swap != "off" else {}),
        **({"arrival_backlog_peak": slo.get("arrival_backlog_peak")}
           if driver is not None else {}),
        **({"slo_attainment": slo.get("slo_attainment"),
            "group_slo_attainment": slo.get("group_slo_attainment")}
           if slo_spec is not None and driver is None else {}),
        **({"policy": engine.policy,
            "aging_promotions": slo.get("aging_promotions")}
           if engine.policy != "fifo" else {}),
        **({"deadline_miss_frac": slo.get("deadline_miss_frac")}
           if slo.get("deadline_miss_frac") is not None else {}),
        **({"priority_slo_attainment":
            slo.get("priority_slo_attainment")}
           if slo.get("priority_slo_attainment") else {}),
        **open_extra}))
    obs.flush()


if __name__ == "__main__":
    main()
