"""Inference entry point: load an exported checkpoint and predict.

Completes the model-surface parity with the reference's HF ecosystem
(the reference's model objects carry ``pipeline``-style inference via
``transformers``; the repo itself only fine-tunes — reference
``scripts/train.py:145,170``). One jitted forward (or the cached
generation loop) per invocation:

  python scripts/predict.py --model_dir /path/to/export --task seq-cls \
      --text "a great movie"
  python scripts/predict.py --model_dir ... --task qa \
      --text "who wrote it?" --context "it was written by Ada."
  python scripts/predict.py --model_dir ... --task seq2seq \
      --text "summarize: ..." --max_new_tokens 48 --num_beams 4
  python scripts/predict.py --model_dir ... --task causal-lm \
      --text "once upon a time" --temperature 0.8 --top_p 0.9
  python scripts/predict.py --model_dir ... --task mlm \
      --text "the capital of france is [MASK]"

Each input line (from ``--text``/``--context`` or ``--input_file``
jsonl with {"text": ..., "context"?: ...}) produces ONE JSON line on
stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.data import load_tokenizer
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models


def _encode_mlm_with_mask(tokenizer, texts, max_length, mask_id):
    """Encode texts containing literal "[MASK]" markers for tokenizers
    that don't recognize the token inline: tokenize the segments around
    each marker and splice the mask id between them."""
    cls_id = getattr(tokenizer, "cls_token_id", None)
    sep_id = getattr(tokenizer, "sep_token_id", None)
    pad_id = getattr(tokenizer, "pad_token_id", 0)
    rows = []
    for text in texts:
        row = [cls_id] if cls_id is not None else []
        parts = text.split("[MASK]")
        for i, part in enumerate(parts):
            if part.strip():
                seg = tokenizer([part], add_special_tokens=False,
                                max_length=max_length)
                am = np.asarray(seg["attention_mask"][0])
                row += [int(x) for x in np.asarray(seg["input_ids"][0])[am > 0]]
            if i < len(parts) - 1:
                row.append(int(mask_id))
        if sep_id is not None:
            row.append(sep_id)
        if int(mask_id) not in row[:max_length] and int(mask_id) in row:
            print(f"warning: [MASK] in {text[:40]!r} fell past "
                  f"--max_seq_length {max_length} and was truncated away",
                  file=sys.stderr)
        rows.append(row[:max_length])
    width = max(len(r) for r in rows)
    ids = np.full((len(rows), width), pad_id, np.int32)
    am = np.zeros((len(rows), width), np.int32)
    for r, row in enumerate(rows):
        ids[r, : len(row)] = row
        am[r, : len(row)] = 1
    return {"input_ids": ids, "attention_mask": am}


def _encode(tokenizer, texts, contexts, max_length):
    # 'longest' keeps the jitted width at the actual batch length
    if contexts is not None:
        return tokenizer(texts, text_pairs=contexts, max_length=max_length,
                         padding="longest")
    return tokenizer(texts, max_length=max_length, padding="longest")


def predict(args) -> list[dict]:
    overrides = {}
    if getattr(args, "kv_cache", "fp") != "fp":
        if args.task != "causal-lm":
            raise SystemExit("--kv_cache int8 is a decode-cache knob "
                             "(Llama family + GPT-2); use --task "
                             "causal-lm")
        overrides["kv_cache_dtype"] = args.kv_cache
    model, params, family, config = auto_models.from_pretrained(
        args.model_dir, task=args.task, num_labels=args.num_labels,
        **overrides)
    tokenizer = load_tokenizer(args.model_dir, vocab_size=config.vocab_size)

    if getattr(args, "adapter", None):
        # LoRA sidecar deployment: merge adapter.safetensors onto the
        # base checkpoint at load (the alternative to shipping the
        # merged export scripts/train.py writes)
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
            load_adapters,
            lora_scaling,
            merge_lora,
        )

        lora, meta = load_adapters(args.adapter)
        params = merge_lora(params, lora,
                            lora_scaling(meta["lora_rank"],
                                         meta["lora_alpha"]))
        print(f"adapter: r={meta['lora_rank']} alpha={meta['lora_alpha']} "
              f"targets={meta['lora_targets']} merged", file=sys.stderr)

    if getattr(args, "quantize", "none") == "int8":
        # int8 weight-only decode (models/quant.py): HBM-bound decode
        # reads 1/4 the kernel bytes; compute stays in the model dtype
        if args.task not in ("causal-lm", "seq2seq"):
            raise SystemExit("--quantize int8 covers the generation tasks "
                             "(--task causal-lm or seq2seq)")
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
            quantize_for_generation,
        )
        model, params, stats = quantize_for_generation(model, params)
        print(f"int8: {stats['kernels_quantized']} kernels, "
              f"{stats['bytes_before']/1e6:.1f} -> "
              f"{stats['bytes_after']/1e6:.1f} MB", file=sys.stderr)

    if args.input_file:
        rows = [json.loads(l) for l in open(args.input_file) if l.strip()]
        texts = [r["text"] for r in rows]
        # context is per-row optional; rows without one get an empty pair
        contexts = ([r.get("context", "") for r in rows]
                    if any("context" in r for r in rows) else None)
    else:
        texts = [args.text]
        contexts = [args.context] if args.context else None

    max_len = min(args.max_seq_length,
                  getattr(config, "max_position_embeddings", args.max_seq_length))
    qa_offsets = None
    if (args.task == "qa" and contexts is not None
            and hasattr(tokenizer, "encode_qa")):
        # QA gets the eval-metric encoding: only_second truncation plus
        # char offsets, so the answer decodes by slicing the ORIGINAL
        # context (exact surface text) with the joint span search
        enc = dict(tokenizer.encode_qa(texts, contexts, max_length=max_len,
                                       return_offsets=True,
                                       doc_stride=args.doc_stride))
        # encode_qa pads to max_length; trim every column to the longest
        # real row (the 'longest' contract of _encode) so the jitted
        # width tracks the batch
        width = max(int(np.asarray(enc["attention_mask"]).sum(1).max()), 1)
        enc = {k: v[:, :width] if getattr(v, "ndim", 1) == 2 else v
               for k, v in enc.items()}
        qa_offsets = (enc["offset_starts"], enc["offset_ends"])
        qa_example_ids = enc.get("example_ids")
    else:
        enc = _encode(tokenizer, texts, contexts, max_len)
    ids = jnp.asarray(enc["input_ids"])
    mask = jnp.asarray(enc["attention_mask"])
    token_types = (jnp.asarray(enc["token_type_ids"])
                   if "token_type_ids" in enc else None)

    results: list[dict] = []
    if args.task in ("seq2seq", "causal-lm"):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
            beam_search_generate,
            generate,
            generate_causal,
        )

        if (getattr(args, "self_speculate_layers", 0)
                and args.task != "causal-lm"):
            raise SystemExit("--self_speculate_layers (layer-skip "
                             "self-speculation) supports --task "
                             "causal-lm only; seq2seq speculation needs "
                             "a separate --draft_dir checkpoint")
        if getattr(args, "prefill_chunk", 0):
            if args.task != "causal-lm":
                raise SystemExit("--prefill_chunk supports --task "
                                 "causal-lm only")
            if (getattr(args, "draft_dir", None)
                    or getattr(args, "self_speculate_layers", 0)):
                raise SystemExit("--prefill_chunk cannot combine with "
                                 "speculative decoding (its prefill is "
                                 "not chunked)")
            if args.num_beams > 1:
                raise SystemExit("--prefill_chunk cannot combine with "
                                 "--num_beams (beam prefill is not "
                                 "chunked)")
        if args.task == "seq2seq":
            if getattr(args, "draft_dir", None):
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
                    generate_speculative_seq2seq,
                )

                if args.num_beams > 1 or args.top_k or args.top_p:
                    raise SystemExit(
                        "--draft_dir for seq2seq supports greedy and "
                        "plain --temperature sampling only (no beams, "
                        "no top-k/top-p)")
                draft_model, draft_params, _, _ = \
                    auto_models.from_pretrained(args.draft_dir,
                                                task="seq2seq")
                out = generate_speculative_seq2seq(
                    model, params, draft_model, draft_params, ids, mask,
                    max_new_tokens=args.max_new_tokens,
                    speculate_k=args.speculate_k,
                    temperature=args.temperature, seed=args.seed)
            elif args.num_beams > 1:
                out = beam_search_generate(model, params, ids, mask,
                                           num_beams=args.num_beams,
                                           max_new_tokens=args.max_new_tokens,
                                           length_penalty=args.length_penalty)
            else:
                out = generate(model, params, ids, mask,
                               max_new_tokens=args.max_new_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k, top_p=args.top_p,
                               seed=args.seed)
        elif (getattr(args, "draft_dir", None)
                or getattr(args, "self_speculate_layers", 0)):
            # speculative decoding: token-exact greedy at temperature 0,
            # distribution-exact rejection sampling at temperature > 0;
            # knobs it can't honor are refused, not silently ignored
            spec_flag = ("--draft_dir" if args.draft_dir
                         else "--self_speculate_layers")
            if (args.top_k or args.top_p) and not args.temperature:
                raise SystemExit(
                    f"{spec_flag}: --top_k/--top_p need --temperature "
                    "> 0 (greedy speculation is argmax, which filtering "
                    "cannot change)")
            if args.num_beams > 1:
                raise SystemExit(f"{spec_flag} cannot combine with "
                                 "--num_beams (speculative decode is "
                                 "greedy)")
            if args.self_speculate_layers < 0:
                raise SystemExit("--self_speculate_layers must be >= 1")
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
                generate_speculative,
                self_draft,
            )

            if args.draft_dir and args.self_speculate_layers:
                raise SystemExit("--draft_dir and --self_speculate_layers "
                                 "are mutually exclusive")
            if args.self_speculate_layers:
                # layer-skip self-speculation: the draft is the target's
                # own first N layers — no second checkpoint
                draft_model, draft_params = self_draft(
                    model, params, args.self_speculate_layers)
            else:
                draft_model, draft_params, _, _ = auto_models.from_pretrained(
                    args.draft_dir, task="causal-lm")
            # bucket prompt widths to multiples of 32 (right-padded
            # masks), batch each bucket in ONE call: rows advance
            # independently inside the batched while_loop, and each
            # bucket width compiles once
            ids_np, mask_np = np.asarray(ids), np.asarray(mask)
            widths = [min(ids_np.shape[1],
                          ((int(mask_np[r].sum()) + 31) // 32) * 32)
                      for r in range(ids_np.shape[0])]
            rows = [None] * ids_np.shape[0]
            for w in sorted(set(widths)):
                sel = [r for r, rw in enumerate(widths) if rw == w]
                outs = np.asarray(generate_speculative(
                    model, params, draft_model, draft_params,
                    ids_np[sel][:, :w], mask_np[sel][:, :w],
                    max_new_tokens=args.max_new_tokens,
                    speculate_k=args.speculate_k,
                    temperature=args.temperature, top_k=args.top_k,
                    top_p=args.top_p, seed=args.seed))
                for i, r in enumerate(sel):
                    rows[r] = outs[i]
            out = np.stack(rows, axis=0)
        elif args.num_beams > 1:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
                beam_search_causal,
            )

            if args.temperature or args.top_k or args.top_p:
                raise SystemExit("--num_beams is deterministic beam "
                                 "search; it cannot combine with "
                                 "--temperature/--top_k/--top_p")
            out = beam_search_causal(model, params, ids, mask,
                                     num_beams=args.num_beams,
                                     max_new_tokens=args.max_new_tokens,
                                     length_penalty=args.length_penalty)
        else:
            out = generate_causal(model, params, ids, mask,
                                  max_new_tokens=args.max_new_tokens,
                                  temperature=args.temperature,
                                  top_k=args.top_k, top_p=args.top_p,
                                  seed=args.seed,
                                  prefill_chunk=getattr(args,
                                                        "prefill_chunk", 0))
        for text, row in zip(texts, np.asarray(out)):
            results.append({"text": text,
                            "generated": tokenizer.decode(row),
                            "generated_ids": row.tolist()})
        return results

    # token_type_ids matter for pair inputs (QA): the trainer forwards
    # them (train/trainer.py::_apply), so inference must too
    # graftlint: allow[R3] no static key: params/ids/mask/type-ids are all traced arrays, the model is closed over — one compile per predict invocation by construction
    apply = jax.jit(lambda p, i, m, t: model.apply(
        {"params": p}, i, m, token_type_ids=t, deterministic=True))
    out = apply(params, ids, mask, token_types)

    if args.task == "seq-cls":
        probs = np.asarray(jax.nn.softmax(out.astype(jnp.float32), -1))
        for text, p in zip(texts, probs):
            results.append({"text": text, "label": int(p.argmax()),
                            "probs": [round(float(x), 4) for x in p]})
    elif args.task == "token-cls":
        pred = np.asarray(jnp.argmax(out, -1))
        am = np.asarray(mask)
        for r, text in enumerate(texts):
            toks = tokenizer.convert_ids_to_tokens(np.asarray(ids[r])[am[r] > 0])
            results.append({"text": text,
                            "tokens": toks,
                            "labels": pred[r][am[r] > 0].tolist()})
    elif args.task == "qa":
        start, end = out
        if qa_offsets is not None:
            # the eval metric's decode (utils/metrics.py): joint argmax
            # over context-token pairs, sliced from the original context;
            # start/end report the SAME winning span, so a result row is
            # internally consistent
            from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
                extract_answer_spans,
            )
            ex_ids = (qa_example_ids if qa_example_ids is not None
                      else np.arange(len(texts)))
            feat_ctx = [contexts[int(ex)] for ex in ex_ids]
            spans = extract_answer_spans(start, end, qa_offsets[0],
                                         qa_offsets[1], feat_ctx,
                                         with_spans=True, with_scores=True)
            # doc-stride: keep each input's highest-scoring window (token
            # indices are relative to THAT window's feature row)
            best = {}
            for (answer, s_tok, e_tok, score), ex in zip(spans, ex_ids):
                ex = int(ex)
                if ex not in best or score > best[ex][3]:
                    best[ex] = (answer, s_tok, e_tok, score)
            for r, text in enumerate(texts):
                answer, s_tok, e_tok, _ = best[r]
                results.append({"text": text, "start": s_tok,
                                "end": e_tok, "answer": answer})
        else:
            s = np.asarray(jnp.argmax(start, -1))
            e = np.asarray(jnp.argmax(end, -1))
            for r, text in enumerate(texts):
                lo, hi = int(s[r]), int(e[r])
                span_ids = np.asarray(ids[r])[lo: hi + 1] if hi >= lo else []
                results.append({"text": text, "start": lo, "end": hi,
                                "answer": tokenizer.decode(span_ids)})
    elif args.task == "rtd":
        # per-token probability that the token was replaced (ELECTRA
        # discriminator; sigmoid of the binary logit)
        probs = np.asarray(jax.nn.sigmoid(out.astype(jnp.float32)))
        am = np.asarray(mask)
        for r, text in enumerate(texts):
            toks = tokenizer.convert_ids_to_tokens(np.asarray(ids[r])[am[r] > 0])
            results.append({"text": text, "tokens": toks,
                            "replaced_prob": [round(float(x), 4)
                                              for x in probs[r][am[r] > 0]]})
    elif args.task == "mlm":
        mask_id = getattr(tokenizer, "mask_token_id", None)
        if mask_id is None:
            # without this, the elementwise ids == None comparison below
            # is all-False and every row silently gets empty 'fills'
            raise ValueError(
                "mlm prediction needs a tokenizer with a mask token "
                "(tokenizer.mask_token_id is None); same loud-failure "
                "convention as ArrayDataset.from_mlm_texts")
        if not np.any(np.asarray(ids) == mask_id):
            # in-repo tokenizers split a literal "[MASK]" into
            # punctuation; re-encode segment-wise around the marker
            enc = _encode_mlm_with_mask(tokenizer, texts, max_len, mask_id)
            ids = jnp.asarray(enc["input_ids"])
            mask = jnp.asarray(enc["attention_mask"])
            out = apply(params, ids, mask, None)
        logits = np.asarray(out)
        for r, text in enumerate(texts):
            row_ids = np.asarray(ids[r])
            fills = []
            for pos in np.flatnonzero(row_ids == mask_id):
                top = np.argsort(-logits[r, pos])[: args.top_k or 5]
                fills.append({"position": int(pos),
                              "top_tokens": tokenizer.convert_ids_to_tokens(top),
                              "top_ids": top.tolist()})
            results.append({"text": text, "fills": fills})
    else:
        raise ValueError(f"unknown task {args.task!r}")
    return results


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model_dir", required=True)
    ap.add_argument("--task", default="seq-cls",
                    choices=["seq-cls", "token-cls", "qa", "seq2seq",
                             "causal-lm", "mlm", "rtd"])
    ap.add_argument("--text", default=None)
    ap.add_argument("--context", default=None)
    ap.add_argument("--input_file", default=None,
                    help="jsonl with {'text': ..., 'context'?: ...}")
    ap.add_argument("--num_labels", type=int, default=2)
    ap.add_argument("--adapter", default=None,
                    help="LoRA adapter dir (adapter.safetensors + "
                         "adapter_config.json) merged onto the base "
                         "checkpoint at load")
    ap.add_argument("--doc_stride", type=int, default=0,
                    help="QA: window long contexts with this token stride "
                         "instead of truncating (HF run_qa; 0 = off)")
    ap.add_argument("--prefill_chunk", type=int, default=0,
                    help="split long-prompt prefill into fixed-size "
                         "chunks (causal-lm; O(chunk) attention memory "
                         "instead of O(prompt), same tokens out)")
    ap.add_argument("--kv_cache", choices=["fp", "int8"], default="fp",
                    help="decode KV cache storage (Llama family + "
                         "GPT-2): int8 halves cache bytes read per "
                         "step at long context")
    ap.add_argument("--draft_dir", default=None,
                    help="draft-model checkpoint dir for speculative "
                         "decoding (causal-lm, or seq2seq for the T5 "
                         "family; greedy-exact at temperature 0: the "
                         "draft changes speed, never tokens)")
    ap.add_argument("--speculate_k", type=int, default=4,
                    help="draft tokens per verify window (--draft_dir / "
                         "--self_speculate_layers)")
    ap.add_argument("--self_speculate_layers", type=int, default=0,
                    help="layer-skip self-speculation: draft = the "
                         "target's own first N layers (no draft "
                         "checkpoint; greedy-exact like --draft_dir)")
    ap.add_argument("--quantize", choices=["none", "int8"], default="none",
                    help="int8 weight-only dense kernels for causal-lm "
                         "generation (HBM-bound decode speedup)")
    ap.add_argument("--max_seq_length", type=int, default=512)
    ap.add_argument("--max_new_tokens", type=int, default=64)
    ap.add_argument("--num_beams", type=int, default=1)
    ap.add_argument("--length_penalty", type=float, default=1.0)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top_k", type=int, default=0)
    ap.add_argument("--top_p", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    return ap


def main(argv=None):
    ap = build_parser()
    args = ap.parse_args(argv)
    if not args.text and not args.input_file:
        ap.error("provide --text or --input_file")
    for row in predict(args):
        print(json.dumps(row))


if __name__ == "__main__":
    main(sys.argv[1:])
