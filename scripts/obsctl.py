#!/usr/bin/env python
"""obsctl — operate on telemetry artifacts from outside the run.

Usage::

    # one merged, deterministic run report (JSON on stdout)
    python scripts/obsctl.py report telemetry/
    # several per-host dirs -> one report; readable rendering; save JSON
    python scripts/obsctl.py report host0/ host1/ host2/ --text -o report.json
    # schema-lint events/trace/flight artifacts (check_telemetry_schema)
    python scripts/obsctl.py validate telemetry/
    # regression triage between two saved reports: step-time/MFU/
    # anomaly/serve-SLO deltas; exit 2 when any metric moves past the
    # threshold in its worse direction (count metrics — anomalies,
    # compiles, preemptions — regress on ANY increase)
    python scripts/obsctl.py diff baseline.json candidate.json --threshold-pct 5

``report`` merges every ``events.jsonl`` it finds under the given
paths (a run dir, per-host dirs, or dirs of per-host subdirs) into one
report: per-host step-time/MFU distributions, compile counts, memory
watermarks, the straggler timeline, the anomaly index, and the serving
SLO summary. The report is validated against its own schema before
printing and the command exits nonzero if it does not pass — a report
you can't trust is worse than none. Schema errors in the INPUT are
carried in the report's ``errors`` field without failing the merge (a
sick host is exactly when you want the report).

Pure stdlib by construction (``obs.report``/``obs.schema`` import
nothing outside the standard library): runs on boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (  # noqa: E402
    build_report,
    diff_reports,
    find_event_files,
    render_diff_text,
    render_text,
    validate_report,
)


def cmd_report(args: argparse.Namespace) -> int:
    if not find_event_files(args.paths):
        print(f"obsctl: no events.jsonl under {', '.join(args.paths)}",
              file=sys.stderr)
        return 1
    report = build_report(args.paths)
    problems = validate_report(report)
    if problems:
        for p in problems:
            print(f"obsctl: invalid report: {p}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"obsctl: wrote {args.out}", file=sys.stderr)
    if args.text:
        sys.stdout.write(render_text(report))
    else:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    return 0


def cmd_diff(args: argparse.Namespace) -> int:
    """Delta two saved reports (``obsctl report -o``). Exit codes:
    0 = no regression, 1 = unreadable/invalid input, 2 = at least one
    metric regressed past the threshold — the shape CI gates on."""
    reports = []
    for path in (args.a, args.b):
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obsctl: cannot read report {path}: {e}",
                  file=sys.stderr)
            return 1
        problems = validate_report(doc)
        if problems:
            for p in problems:
                print(f"obsctl: invalid report {path}: {p}",
                      file=sys.stderr)
            return 1
        reports.append(doc)
    diff = diff_reports(reports[0], reports[1],
                        threshold_pct=args.threshold_pct)
    if args.text:
        sys.stdout.write(render_diff_text(diff))
    else:
        json.dump(diff, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    if diff["regressions"]:
        print(f"obsctl: {len(diff['regressions'])} regression(s): "
              f"{', '.join(diff['regressions'])}", file=sys.stderr)
        return 2
    return 0


def cmd_validate(args: argparse.Namespace) -> int:
    from scripts.check_telemetry_schema import main as check_main

    return check_main(args.paths)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="obsctl", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    rep = sub.add_parser("report",
                         help="merge per-host telemetry into one run report")
    rep.add_argument("paths", nargs="+",
                     help="telemetry dir(s), per-host dirs, or event files")
    rep.add_argument("--text", action="store_true",
                     help="readable rendering instead of JSON")
    rep.add_argument("-o", "--out", default=None,
                     help="also write the JSON report to this path")
    rep.set_defaults(func=cmd_report)

    dif = sub.add_parser("diff",
                         help="step-time/MFU/anomaly/serve-SLO deltas "
                              "between two saved reports (exit 2 over "
                              "the threshold)")
    dif.add_argument("a", help="baseline report JSON (obsctl report -o)")
    dif.add_argument("b", help="candidate report JSON")
    dif.add_argument("--threshold-pct", type=float, default=5.0,
                     help="relative worsening that counts as a "
                          "regression for ratio metrics (default 5)")
    dif.add_argument("--text", action="store_true",
                     help="readable rendering instead of JSON")
    dif.set_defaults(func=cmd_diff)

    val = sub.add_parser("validate",
                         help="schema-lint telemetry artifacts "
                              "(check_telemetry_schema)")
    val.add_argument("paths", nargs="+")
    val.set_defaults(func=cmd_validate)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
