#!/usr/bin/env python
"""obsctl — operate on telemetry artifacts from outside the run.

Usage::

    # one merged, deterministic run report (JSON on stdout)
    python scripts/obsctl.py report telemetry/
    # several per-host dirs -> one report; readable rendering; save JSON
    python scripts/obsctl.py report host0/ host1/ host2/ --text -o report.json
    # schema-lint events/trace/flight artifacts (check_telemetry_schema)
    python scripts/obsctl.py validate telemetry/
    # regression triage between two saved reports: step-time/MFU/
    # anomaly/serve-SLO deltas; exit 2 when any metric moves past the
    # threshold in its worse direction (count metrics — anomalies,
    # compiles, preemptions — regress on ANY increase)
    python scripts/obsctl.py diff baseline.json candidate.json --threshold-pct 5
    # per-request lifecycle Gantt rows from request_timeline events,
    # plus a Chrome-trace export (load in Perfetto / chrome://tracing)
    python scripts/obsctl.py timeline telemetry/ --trace serve_trace.json
    # SLO attribution: which phase the tail requests burned their
    # budget in (queue / prefill / decode / preempted / overhead),
    # aggregated per request group (the per-tenant hook)
    python scripts/obsctl.py slo telemetry/ --percentile 99 --text
    # one stitched cross-engine trace (a multi-replica/disaggregated
    # run): the causal narrative of where the request's latency went
    python scripts/obsctl.py trace t000002 telemetry/
    # fleet SLO attribution over every stitched trace, plus the merged
    # multi-track Chrome export (one pid per replica, transport arrows)
    python scripts/obsctl.py fleet telemetry/ --trace fleet_trace.json
    # follow a LIVE events.jsonl: rolling waiting-depth / KV-pressure /
    # decode tokens/sec / TTFT percentiles (and, on open-loop streams,
    # rolling SLO attainment) over a sliding window, reading only what
    # was appended since the last poll
    python scripts/obsctl.py tail telemetry/events.jsonl --window 64
    # open-loop goodput replay: SLO attainment / goodput tokens per
    # arrival rate and tenant, per-phase miss attribution, capacity
    # knee across a rate sweep; exit 2 when overall attainment falls
    # below the floor
    python scripts/obsctl.py goodput telemetry/ --min-attainment 0.99
    # static analysis (graftlint): enforce the compile-flatness /
    # host-sync / contract invariants over the tree (or a stdin
    # snippet); exit 2 on unsuppressed findings, like diff
    python scripts/obsctl.py lint
    cat patch.py | python scripts/obsctl.py lint - --format json

``report`` merges every ``events.jsonl`` it finds under the given
paths (a run dir, per-host dirs, or dirs of per-host subdirs) into one
report: per-host step-time/MFU distributions, compile counts, memory
watermarks, the straggler timeline, the anomaly index, and the serving
SLO summary. The report is validated against its own schema before
printing and the command exits nonzero if it does not pass — a report
you can't trust is worse than none. Schema errors in the INPUT are
carried in the report's ``errors`` field without failing the merge (a
sick host is exactly when you want the report).

Pure stdlib by construction (``obs.report``/``obs.schema`` import
nothing outside the standard library): runs on boxes without jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (  # noqa: E402
    build_report,
    diff_reports,
    find_event_files,
    render_diff_text,
    render_text,
    validate_report,
)


def cmd_report(args: argparse.Namespace) -> int:
    if not find_event_files(args.paths):
        print(f"obsctl: no events.jsonl under {', '.join(args.paths)}",
              file=sys.stderr)
        return 1
    report = build_report(args.paths)
    problems = validate_report(report)
    if problems:
        for p in problems:
            print(f"obsctl: invalid report: {p}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"obsctl: wrote {args.out}", file=sys.stderr)
    if args.text:
        sys.stdout.write(render_text(report))
    else:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    return 0


def cmd_diff(args: argparse.Namespace) -> int:
    """Delta two saved reports (``obsctl report -o``). Exit codes:
    0 = no regression, 1 = unreadable/invalid input, 2 = at least one
    metric regressed past the threshold — the shape CI gates on."""
    reports = []
    for path in (args.a, args.b):
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obsctl: cannot read report {path}: {e}",
                  file=sys.stderr)
            return 1
        problems = validate_report(doc)
        if problems:
            for p in problems:
                print(f"obsctl: invalid report {path}: {p}",
                      file=sys.stderr)
            return 1
        reports.append(doc)
    diff = diff_reports(reports[0], reports[1],
                        threshold_pct=args.threshold_pct)
    if args.text:
        sys.stdout.write(render_diff_text(diff))
    else:
        json.dump(diff, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    if diff["regressions"]:
        print(f"obsctl: {len(diff['regressions'])} regression(s): "
              f"{', '.join(diff['regressions'])}", file=sys.stderr)
        return 2
    return 0


def cmd_validate(args: argparse.Namespace) -> int:
    from scripts.check_telemetry_schema import main as check_main

    return check_main(args.paths)


def _load_timelines(paths) -> "tuple[list[dict], int]":
    """(records, rc): strictly load + fold request_timeline events;
    rc 1 with stderr diagnostics on malformed/inconsistent input (a
    timeline built from a half-trusted stream is worse than none)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        check_decomposition,
        collect_timelines,
        load_events,
    )

    events, errors = load_events(paths)
    if errors:
        for e in errors[:20]:
            print(f"obsctl: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"obsctl: ... and {len(errors) - 20} more",
                  file=sys.stderr)
        return [], 1
    records = collect_timelines(events)
    problems = [m for rec in records for m in check_decomposition(rec)]
    if problems:
        for p in problems[:20]:
            print(f"obsctl: inconsistent timeline: {p}", file=sys.stderr)
        return [], 1
    return records, 0


def cmd_timeline(args: argparse.Namespace) -> int:
    """Per-request Gantt reconstruction + Chrome-trace export. Output
    is deterministic (byte-identical across input orderings); exit 1 on
    malformed input or no request_timeline events."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        gantt_text,
        write_chrome_trace,
    )

    if args.width < 4:
        print(f"obsctl: --width must be >= 4, got {args.width}",
              file=sys.stderr)
        return 1
    records, rc = _load_timelines(args.paths)
    if rc:
        return rc
    if not records:
        print("obsctl: no request_timeline events (serve run with "
              "HSTD_SERVE_TIMELINE=off, or not a serve run?)",
              file=sys.stderr)
        return 1
    if args.trace:
        write_chrome_trace(records, args.trace)
        print(f"obsctl: wrote {args.trace}", file=sys.stderr)
    if args.json:
        json.dump(records, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(gantt_text(records, width=args.width))
    return 0


def cmd_slo(args: argparse.Namespace) -> int:
    """SLO attribution: phase decomposition of the latency tail, per
    group — same strict-input and determinism contract as timeline."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        render_slo_text,
        slo_attribution,
    )

    if not 0 < args.percentile <= 100:
        print(f"obsctl: --percentile must be in (0, 100], got "
              f"{args.percentile}", file=sys.stderr)
        return 1
    records, rc = _load_timelines(args.paths)
    if rc:
        return rc
    if not records:
        print("obsctl: no request_timeline events to attribute",
              file=sys.stderr)
        return 1
    doc = slo_attribution(records, pct=args.percentile / 100.0)
    if args.text:
        sys.stdout.write(render_slo_text(doc))
    else:
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    return 0


def cmd_lint(args: argparse.Namespace) -> int:
    """graftlint over the tree (or one stdin snippet with ``-``): the
    same run/renderers/exit codes as ``scripts/graftlint.py`` — 0
    clean, 1 bad input, 2 unsuppressed findings. Stdlib-only like
    every obsctl command (rule R1 lints the linter itself)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
        LintInputError,
        lint_text,
        render_json,
        render_text,
        run_lint,
    )

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        if args.paths == ["-"]:
            result = lint_text(sys.stdin.read(), rules=rules)
        elif "-" in args.paths:
            print("obsctl: '-' cannot be combined with file paths",
                  file=sys.stderr)
            return 1
        else:
            root = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            result = run_lint(root, paths=args.paths or None,
                              rules=rules)
    except LintInputError as e:
        print(f"obsctl: {e}", file=sys.stderr)
        return 1
    if args.format == "json":
        sys.stdout.write(render_json(result))
    else:
        sys.stdout.write(render_text(result))
    return 2 if result.active else 0


def cmd_goodput(args: argparse.Namespace) -> int:
    """Open-loop goodput replay (ISSUE 16): split a recorded stream
    into its ``open_loop`` runs, compute SLO attainment / goodput /
    per-phase miss attribution per run and per swept arrival rate, and
    locate the capacity knee. Same strict-input contract as timeline
    (rc 1 on malformed), same deterministic-bytes contract (sorted
    keys, input-order-independent), and diff-style exit codes: rc 2
    when the overall attainment falls below ``--min-attainment``."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.goodput import (
        goodput,
        render_goodput_text,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        load_events,
    )

    if not 0 < args.knee_target <= 1:
        print(f"obsctl: --knee-target must be in (0, 1], got "
              f"{args.knee_target}", file=sys.stderr)
        return 1
    if args.min_attainment is not None \
            and not 0 <= args.min_attainment <= 1:
        print(f"obsctl: --min-attainment must be in [0, 1], got "
              f"{args.min_attainment}", file=sys.stderr)
        return 1
    events, errors = load_events(args.paths)
    if errors:
        for e in errors[:20]:
            print(f"obsctl: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"obsctl: ... and {len(errors) - 20} more",
                  file=sys.stderr)
        return 1
    doc = goodput(events, knee_target=args.knee_target)
    if not doc.get("runs"):
        print("obsctl: no open_loop events (closed-loop trace, or not "
              "a serve run?)", file=sys.stderr)
        return 1
    if args.text:
        sys.stdout.write(render_goodput_text(doc))
    else:
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    attainment = doc.get("overall_attainment")
    if args.min_attainment is not None:
        if attainment is None:
            print("obsctl: --min-attainment set but no run carried "
                  "SLO verdicts", file=sys.stderr)
            return 1
        if attainment < args.min_attainment:
            print(f"obsctl: attainment {attainment} below the "
                  f"--min-attainment floor {args.min_attainment}",
                  file=sys.stderr)
            return 2
    return 0


def _load_traces(paths) -> "tuple[list[dict], int]":
    """(stitched traces, rc): strictly load the stream and stitch it
    (ISSUE 19) — same strict-input contract as ``_load_timelines``."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        load_events,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.trace import (
        collect_traces,
    )

    events, errors = load_events(paths)
    if errors:
        for e in errors[:20]:
            print(f"obsctl: {e}", file=sys.stderr)
        if len(errors) > 20:
            print(f"obsctl: ... and {len(errors) - 20} more",
                  file=sys.stderr)
        return [], 1
    return collect_traces(events), 0


def cmd_trace(args: argparse.Namespace) -> int:
    """One stitched cross-engine trace as a causal narrative (ISSUE
    19). ``id`` selects by trace_id (``t000002``) or request id.
    Deterministic bytes under any input order. Exit 0 on a complete,
    decomposition-clean trace; 1 on malformed input, an unknown id, an
    INCOMPLETE trace (flagged, still rendered) or a decomposition
    error — never a silently wrong narrative."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.trace import (
        check_trace,
        trace_text,
    )

    traces, rc = _load_traces(args.paths)
    if rc:
        return rc
    if not traces:
        print("obsctl: no traced serve events (single-replica run, or "
              "HSTD_SERVE_TRACE=off?)", file=sys.stderr)
        return 1
    want = str(args.id)
    sel = [t for t in traces
           if t["trace_id"] == want or str(t.get("request")) == want]
    if not sel:
        known = ", ".join(t["trace_id"] for t in traces[:8])
        print(f"obsctl: no trace {want!r} (known: {known}"
              f"{', ...' if len(traces) > 8 else ''})", file=sys.stderr)
        return 1
    bad = 0
    for tr in sel:
        sys.stdout.write(trace_text(tr))
        if not tr["complete"] or check_trace(tr):
            bad += 1
    return 1 if bad else 0


def cmd_fleet(args: argparse.Namespace) -> int:
    """Fleet SLO-attribution rollup over every stitched trace (ISSUE
    19), with the merged multi-track Chrome export (one pid per
    replica, transport hops as flow arrows) behind ``--trace``.
    Incomplete traces are FLAGGED in the output and exit 0 (a torn
    tail is an input condition, not a wrongness); a decomposition
    error on a claimed-complete trace exits 1."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.trace import (
        check_trace,
        fleet_chrome_trace,
        fleet_summary,
        fleet_text,
    )

    traces, rc = _load_traces(args.paths)
    if rc:
        return rc
    if not traces:
        print("obsctl: no traced serve events (single-replica run, or "
              "HSTD_SERVE_TRACE=off?)", file=sys.stderr)
        return 1
    problems = [m for tr in traces for m in check_trace(tr)]
    if problems:
        for p in problems[:20]:
            print(f"obsctl: inconsistent trace: {p}", file=sys.stderr)
        return 1
    if args.trace:
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(fleet_chrome_trace(traces), f, sort_keys=True)
            f.write("\n")
        print(f"obsctl: wrote {args.trace}", file=sys.stderr)
    if args.json:
        json.dump(fleet_summary(traces), sys.stdout, indent=2,
                  sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(fleet_text(traces))
    return 0


def cmd_tail(args: argparse.Namespace) -> int:
    """Follow a live events.jsonl: each poll reads only the appended
    suffix (the prefix is never re-read), updates the sliding-window
    gauges, and prints one line per poll that saw new events. Exits
    after ``--updates`` lines (0 = follow forever), or rc 1 the moment
    a malformed complete line lands."""
    import time as _time

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        TailFollower,
        TailStats,
    )

    if args.window < 1:
        print(f"obsctl: --window must be >= 1, got {args.window}",
              file=sys.stderr)
        return 1
    if args.interval < 0:
        print(f"obsctl: --interval must be >= 0, got {args.interval}",
              file=sys.stderr)
        return 1
    if not os.path.isfile(args.path):
        print(f"obsctl: no such file {args.path}", file=sys.stderr)
        return 1
    follower = TailFollower(args.path)
    stats = TailStats(window=args.window)
    updates = 0
    try:
        while True:
            events, errors = follower.poll()
            if errors:
                for e in errors[:20]:
                    print(f"obsctl: {e}", file=sys.stderr)
                return 1
            if events:
                for e in events:
                    stats.update(e)
                print(stats.render(), flush=True)
                updates += 1
                if args.updates and updates >= args.updates:
                    return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="obsctl", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    rep = sub.add_parser("report",
                         help="merge per-host telemetry into one run report")
    rep.add_argument("paths", nargs="+",
                     help="telemetry dir(s), per-host dirs, or event files")
    rep.add_argument("--text", action="store_true",
                     help="readable rendering instead of JSON")
    rep.add_argument("-o", "--out", default=None,
                     help="also write the JSON report to this path")
    rep.set_defaults(func=cmd_report)

    dif = sub.add_parser("diff",
                         help="step-time/MFU/anomaly/serve-SLO deltas "
                              "between two saved reports (exit 2 over "
                              "the threshold)")
    dif.add_argument("a", help="baseline report JSON (obsctl report -o)")
    dif.add_argument("b", help="candidate report JSON")
    dif.add_argument("--threshold-pct", type=float, default=5.0,
                     help="relative worsening that counts as a "
                          "regression for ratio metrics (default 5)")
    dif.add_argument("--text", action="store_true",
                     help="readable rendering instead of JSON")
    dif.set_defaults(func=cmd_diff)

    val = sub.add_parser("validate",
                         help="schema-lint telemetry artifacts "
                              "(check_telemetry_schema)")
    val.add_argument("paths", nargs="+")
    val.set_defaults(func=cmd_validate)

    tim = sub.add_parser("timeline",
                         help="per-request lifecycle Gantt rows + "
                              "Chrome-trace export from "
                              "request_timeline events")
    tim.add_argument("paths", nargs="+",
                     help="telemetry dir(s) or event files")
    tim.add_argument("--trace", default=None,
                     help="also write a Chrome-trace JSON here "
                          "(Perfetto / chrome://tracing)")
    tim.add_argument("--json", action="store_true",
                     help="raw timeline records as JSON instead of "
                          "the Gantt rendering")
    tim.add_argument("--width", type=int, default=48,
                     help="Gantt row width in cells (default 48)")
    tim.set_defaults(func=cmd_timeline)

    slo = sub.add_parser("slo",
                         help="SLO attribution: which phase the "
                              "latency tail burned its budget in, "
                              "per request group")
    slo.add_argument("paths", nargs="+",
                     help="telemetry dir(s) or event files")
    slo.add_argument("--percentile", type=float, default=99.0,
                     help="tail threshold percentile (default 99)")
    slo.add_argument("--text", action="store_true",
                     help="readable rendering instead of JSON")
    slo.set_defaults(func=cmd_slo)

    trc = sub.add_parser("trace",
                         help="one stitched cross-engine request "
                              "trace as a causal narrative (by "
                              "trace_id or request id)")
    trc.add_argument("id", help="trace_id (t000002) or request id")
    trc.add_argument("paths", nargs="+",
                     help="telemetry dir(s) or event files")
    trc.set_defaults(func=cmd_trace)

    flt = sub.add_parser("fleet",
                         help="fleet SLO-attribution rollup over "
                              "stitched traces + merged multi-track "
                              "Chrome export (--trace)")
    flt.add_argument("paths", nargs="+",
                     help="telemetry dir(s) or event files")
    flt.add_argument("--trace", default=None,
                     help="write the merged multi-track Chrome-trace "
                          "JSON here (one pid per replica, transport "
                          "flow arrows)")
    flt.add_argument("--json", action="store_true",
                     help="raw fleet summary as JSON instead of the "
                          "table rendering")
    flt.set_defaults(func=cmd_fleet)

    good = sub.add_parser("goodput",
                          help="open-loop goodput replay: SLO "
                               "attainment per arrival rate/tenant, "
                               "miss attribution, capacity knee "
                               "(exit 2 below --min-attainment)")
    good.add_argument("paths", nargs="+",
                      help="telemetry dir(s) or event files")
    good.add_argument("--min-attainment", type=float, default=None,
                      help="exit 2 when overall attainment falls "
                           "below this fraction")
    good.add_argument("--knee-target", type=float, default=0.99,
                      help="attainment below this marks the capacity "
                           "knee in a rate sweep (default 0.99)")
    good.add_argument("--text", action="store_true",
                      help="readable rendering instead of JSON")
    good.set_defaults(func=cmd_goodput)

    tail = sub.add_parser("tail",
                          help="follow a live events.jsonl: rolling "
                               "waiting-depth/KV-pressure/tokens-per-"
                               "sec/TTFT over a sliding window")
    tail.add_argument("path", help="an events.jsonl being appended to")
    tail.add_argument("--window", type=int, default=64,
                      help="sliding-window sample count (default 64)")
    tail.add_argument("--interval", type=float, default=0.5,
                      help="poll interval seconds (default 0.5)")
    tail.add_argument("--updates", type=int, default=0,
                      help="exit after N update lines (0 = follow "
                           "forever)")
    tail.set_defaults(func=cmd_tail)

    lint = sub.add_parser("lint",
                          help="graftlint static analysis: compile-"
                               "flatness / host-sync / contract "
                               "invariants (exit 2 on findings)")
    lint.add_argument("paths", nargs="*",
                      help="repo-relative files (default: the whole "
                           "tree); '-' lints stdin with the "
                           "file-local rules")
    lint.add_argument("--format", choices=("text", "json"),
                      default="text")
    lint.add_argument("--rules", default=None,
                      help="comma-separated rule ids (default: all)")
    lint.set_defaults(func=cmd_lint)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
