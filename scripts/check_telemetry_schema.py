#!/usr/bin/env python
"""Lint telemetry artifacts against the event schema.

Usage::

    python scripts/check_telemetry_schema.py telemetry/events.jsonl \
        telemetry/trace.json
    python scripts/check_telemetry_schema.py <out_dir>/telemetry

``*.jsonl`` paths are validated as event streams, ``*.json`` as Chrome
traces; a directory validates the ``events.jsonl``/``trace.json`` it
contains. Pure stdlib by construction — ``obs.schema`` imports nothing
outside the standard library — so this runs on boxes without jax (CI
lint steps, the bench driver). Exit 0 iff every file parses, every
event carries the envelope + per-type required fields, and at least one
valid event exists per file (an empty artifact is a failure: it means
the instrumented run emitted nothing). A torn FINAL jsonl line is
tolerated (crash-safe append contract); torn middle lines are not.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (  # noqa: E402
    validate_events_file,
    validate_trace_file,
)


def expand(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            found = [os.path.join(p, n) for n in sorted(os.listdir(p))
                     if n in ("events.jsonl", "trace.json")
                     or (n.endswith(".jsonl")
                         and n.startswith(("events.host", "flight_")))]
            if not found:
                out.append(os.path.join(p, "events.jsonl"))  # report missing
            out.extend(found)
        else:
            out.append(p)
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="+",
                        help="events.jsonl / trace.json files or a "
                             "telemetry directory")
    parser.add_argument("--strict-tail", action="store_true",
                        help="reject a torn final jsonl line too")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    failed = False
    for path in expand(args.paths):
        if not os.path.exists(path):
            print(f"FAIL {path}: missing")
            failed = True
            continue
        if path.endswith(".jsonl"):
            count, errors = validate_events_file(
                path, strict_tail=args.strict_tail)
            kind = "events"
        else:
            count, errors = validate_trace_file(path)
            kind = "trace events"
        if count == 0 and not errors:
            errors = ["no valid events (empty artifact)"]
        if errors:
            failed = True
            print(f"FAIL {path}: {count} valid {kind}, "
                  f"{len(errors)} error(s)")
            for e in errors[:20]:
                print(f"  {e}")
            if len(errors) > 20:
                print(f"  ... and {len(errors) - 20} more")
        elif not args.quiet:
            print(f"OK   {path}: {count} valid {kind}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
