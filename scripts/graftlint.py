#!/usr/bin/env python
"""graftlint — in-repo static analysis enforcing the engine's
compile-flatness, host-sync, and contract invariants.

Usage::

    # lint the whole tree (package + scripts/ + bench.py + launch.py)
    python scripts/graftlint.py
    # specific files, machine-readable output
    python scripts/graftlint.py huggingface_sagemaker_tensorflow_distributed_tpu/serve/engine.py --format json
    # lint a snippet from stdin (file-local rules only)
    cat patch.py | python scripts/graftlint.py -
    # the rule catalog
    python scripts/graftlint.py --list-rules

Rules (R1–R6; see README "Static analysis" for the full catalog):
jax-free zones, host-sync-in-hot-path, jit-static-key-hygiene,
telemetry-field-contract, env-knob-registry, blockmanager-discipline.
Suppress one finding with ``# graftlint: allow[R2] reason`` on the
offending line (or alone on the line above); the reason is mandatory.

Exit codes match ``obsctl diff``: 0 clean, 1 bad input, 2 unsuppressed
findings. Output is byte-deterministic for a given tree.

Pure stdlib by construction (``analysis`` imports nothing outside the
standard library): runs on boxes without jax — and rule R1 keeps it
that way.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (  # noqa: E402
    LintInputError,
    lint_text,
    render_json,
    render_text,
    run_lint,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.rules import (  # noqa: E402
    RULES,
)


def _list_rules() -> int:
    for rid in sorted(RULES):
        rule = RULES[rid]
        print(f"{rid}  {rule.title}")
        print(f"    {rule.rationale}")
    return 0


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(prog="graftlint",
                                     description=__doc__)
    parser.add_argument("paths", nargs="*",
                        help="repo-relative files to lint (default: "
                             "the whole tree); '-' reads one source "
                             "from stdin and runs the file-local "
                             "rules")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--root", default=_REPO_ROOT,
                        help=argparse.SUPPRESS)
    parser.add_argument("--verbose", action="store_true",
                        help="also list suppressed findings (text "
                             "format; JSON always carries them)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        return _list_rules()

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        if args.paths == ["-"]:
            result = lint_text(sys.stdin.read(), rules=rules)
        elif "-" in args.paths:
            print("graftlint: '-' cannot be combined with file paths",
                  file=sys.stderr)
            return 1
        else:
            result = run_lint(args.root, paths=args.paths or None,
                              rules=rules)
    except LintInputError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 1
    if args.format == "json":
        sys.stdout.write(render_json(result))
    else:
        sys.stdout.write(render_text(result, verbose=args.verbose))
    return 2 if result.active else 0


if __name__ == "__main__":
    sys.exit(main())
