"""Single-node trainer — parity alias.

The reference ships a separate ``MirroredStrategy`` trainer
(``scripts/singe_node_train.py`` — typo in the reference filename) because
its multi-worker path needs Horovod rank juggling. In this framework
distribution is ambient in the mesh, so single-node IS the same program;
this alias exists for launcher/entry-point parity (reference
``launch.py:39-40`` swaps entry points) and disables the world-size LR
scaling exactly as the reference's single-node script does (it compiles a
plain ``Adam(lr)``, ``singe_node_train.py:78``).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from train import main as _main  # noqa: E402


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--scale_lr_by_world_size" not in " ".join(argv):
        argv += ["--scale_lr_by_world_size", "false"]
    return _main(argv)


if __name__ == "__main__":
    main()
