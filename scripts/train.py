"""Distributed fine-tuning entry point.

Parity with reference ``scripts/train.py`` (the multi-worker Horovod/SMDDP
trainer): hyperparameters arrive as CLI args (platform-serialized, with
``SM_*``/``TPU_*`` env defaults for the output dirs), the model is
fine-tuned data-parallel with world-size LR scaling, per-epoch history +
``train_runtime`` land in ``train_results.txt``, eval metrics in
``eval_results.txt``, and model + tokenizer are exported in HF layout to
``model_dir``.

Unlike the reference there is no separate single-node script needed:
distribution is ambient in the mesh (1 chip, 8 chips, multi-host slice —
same code; ``scripts/single_node_train.py`` is a thin alias kept for
launcher parity). Beyond the reference: checkpoint/resume
(the reference commented it out), per-host dataset sharding (the
reference trains on K× data with K workers), typed config (its
``--learning_rate`` was a str), host-0-gated writes.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig, parse_args
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    load_tokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    load_qa,
    load_seq2seq,
    load_text_classification,
    load_token_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    enable_compilation_cache,
    initialize_distributed,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer
from huggingface_sagemaker_tensorflow_distributed_tpu.train.checkpoint import Checkpointer
from huggingface_sagemaker_tensorflow_distributed_tpu.utils import (
    get_logger,
    setup_logging,
    write_results_file,
)

import jax.numpy as jnp

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}


def _check_num_labels(labels, num_labels: int, task: str) -> None:
    """Out-of-range labels would be silently clamped by the gather inside
    the jitted CE loss — fail loudly at data-build time instead."""
    top = max((l for l in labels if l >= 0), default=0)
    if top >= num_labels:
        raise ValueError(
            f"{task}: dataset contains label {top} but --num_labels is "
            f"{num_labels}; pass --num_labels {top + 1} (conll2003 needs 9)")


def build_streaming_dataset(config: TrainConfig, tokenizer, split: str,
                            max_len: int, max_samples, model_config=None):
    """--streaming true: corpus stays on disk, tokenized per batch
    (fixes the reference's materialize-everything quirk, reference
    ``scripts/train.py:80-83``). Sources: ``dataset_path/{split}.jsonl``
    or ``.txt``; the synthetic tier writes its corpus to a cached file
    once so the path is identical to a real on-disk corpus."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.streaming import (
        LineCorpus,
        StreamingTextDataset,
    )

    if config.dataset_path:
        base = os.path.join(config.dataset_path, split)
        path = next((base + ext for ext in (".jsonl", ".txt")
                     if os.path.exists(base + ext)), None)
        if path is None:
            raise ValueError(f"--streaming: no {base}.jsonl or .txt")
    elif config.dataset == "synthetic":
        import json as _json
        import tempfile

        n = max_samples or 2000
        path = os.path.join(
            tempfile.gettempdir(),
            f"stream_synth_{config.task}_{split}_{n}_{config.seed}.jsonl")
        if not os.path.exists(path):
            if config.task == "seq2seq":
                sources, targets = load_seq2seq(
                    "synthetic", split, max_samples=n, seed=config.seed)
                rows = [{"source": s, "target": t}
                        for s, t in zip(sources, targets)]
            else:
                texts, labels = load_text_classification(
                    "synthetic", split, max_samples=n, seed=config.seed)
                rows = [{"text": t, "label": l}
                        for t, l in zip(texts, labels)]
            # per-process unique tmp + atomic replace: multiple local
            # hosts may race to build the same (deterministic) cache file
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                for rec in rows:
                    f.write(_json.dumps(rec) + "\n")
            os.replace(tmp, path)
    else:
        raise ValueError(
            "--streaming needs --dataset_path (train.jsonl/.txt) or "
            "--dataset synthetic")
    corpus = LineCorpus(path, max_rows=max_samples)
    seq2seq_kwargs = None
    if config.task == "seq2seq":
        seq2seq_kwargs = dict(
            max_target_length=config.max_target_length,
            decoder_start_token_id=getattr(model_config,
                                           "decoder_start_token_id", 0),
            pad_token_id=getattr(model_config, "pad_token_id", 0),
            eos_token_id=getattr(model_config, "eos_token_id", 1))
    return StreamingTextDataset(corpus, tokenizer, task=config.task,
                                max_length=max_len, seed=config.seed,
                                num_labels=config.num_labels
                                if config.task == "seq-cls" else None,
                                seq2seq_kwargs=seq2seq_kwargs)


def build_dataset(config: TrainConfig, tokenizer, split: str, max_len: int,
                  max_samples, model_config=None) -> ArrayDataset:
    """Task-specific load+tokenize: seq-cls (reference parity), token-cls
    (CoNLL), extractive QA (SQuAD), seq2seq (CNN-DM) — each with a
    synthetic offline tier."""
    kw = dict(dataset_path=config.dataset_path, max_samples=max_samples,
              seed=config.seed)
    if config.streaming and split == "train":
        return build_streaming_dataset(config, tokenizer, split, max_len,
                                       max_samples, model_config)
    if config.task == "seq-cls":
        texts, labels = load_text_classification(config.dataset, split, **kw)
        _check_num_labels(labels, config.num_labels, config.task)
        return ArrayDataset.from_texts(tokenizer, texts, labels, max_len)
    if config.task == "causal-lm":
        # any text source works as an LM corpus; classification labels
        # are simply ignored
        texts, _ = load_text_classification(config.dataset, split, **kw)
        ds = ArrayDataset.from_lm_texts(
            tokenizer, texts, max_len,
            packed=config.packed_sequences,
            eos_token_id=getattr(model_config, "eos_token_id", None))
        if config.segment_packing:
            # token packing with per-example boundaries: segment ids +
            # restarting positions keep attention and loss per-example
            # exact (vs packed_sequences' cross-document attention)
            ds = ds.pack(max_len, causal=True)
        return ds
    if config.task == "mlm":
        texts, _ = load_text_classification(config.dataset, split, **kw)
        ds = ArrayDataset.from_mlm_texts(
            tokenizer, texts, max_len, seed=config.seed,
            static_masking=config.mlm_static_masking)
        if config.segment_packing:
            # MlmDataset.pack enforces the static-masking requirement;
            # re-raise with the CLI flag spelled out
            if not config.mlm_static_masking:
                raise ValueError(
                    "--segment_packing with task=mlm requires "
                    "--mlm_static_masking true (packing freezes the "
                    "masking draw at build time)")
            ds = ds.pack(max_len)
        return ds
    if config.task == "rtd":
        texts, _ = load_text_classification(config.dataset, split, **kw)
        return ArrayDataset.from_rtd_texts(tokenizer, texts, max_len,
                                           seed=config.seed)
    if config.task == "token-cls":
        sents, tags = load_token_classification(config.dataset, split, **kw)
        _check_num_labels([t for ts in tags for t in ts], config.num_labels,
                          config.task)
        return ArrayDataset.from_token_classification(tokenizer, sents, tags, max_len)
    if config.task == "qa":
        questions, contexts, starts, answers = load_qa(config.dataset, split, **kw)
        return ArrayDataset.from_qa(tokenizer, questions, contexts, starts,
                                    answers, max_len,
                                    doc_stride=config.qa_doc_stride)
    if config.task == "seq2seq" and config.span_corruption:
        try:
            texts, _ = load_text_classification(config.dataset, split, **kw)
        except ValueError:
            # seq2seq-registry datasets (cnn_dailymail, ...) work as a
            # plain text corpus: corrupt the source documents
            texts, _ = load_seq2seq(config.dataset, split, **kw)
        # a corrupted 512-token source needs ~0.2*len target tokens
        # (spans + sentinels + final sentinel); the task default of 64
        # would truncate spans away silently
        needed = int(max_len * 0.2) + 4
        tgt_len = max(config.max_target_length, needed)
        if tgt_len != config.max_target_length:
            get_logger("train").info(
                "span_corruption: raising max_target_length %d → %d to fit "
                "the corrupted spans", config.max_target_length, tgt_len)
        return ArrayDataset.from_span_corruption_texts(
            tokenizer, texts, max_source_length=max_len,
            max_target_length=tgt_len,
            decoder_start_token_id=getattr(model_config,
                                           "decoder_start_token_id", 0),
            pad_token_id=getattr(model_config, "pad_token_id", 0),
            eos_token_id=getattr(model_config, "eos_token_id", 1),
            seed=config.seed)
    if config.task == "seq2seq":
        sources, targets = load_seq2seq(config.dataset, split, **kw)
        return ArrayDataset.from_seq2seq(
            tokenizer, sources, targets, max_source_length=max_len,
            max_target_length=config.max_target_length,
            decoder_start_token_id=getattr(model_config,
                                           "decoder_start_token_id", 0),
            pad_token_id=getattr(model_config, "pad_token_id", 0),
            eos_token_id=getattr(model_config, "eos_token_id", 1))
    raise ValueError(f"no data path for task {config.task!r}")


def main(argv=None) -> dict:
    config = parse_args(argv)
    process_index, process_count = initialize_distributed()
    enable_compilation_cache(config.compilation_cache_dir)
    setup_logging(process_index=process_index, all_hosts=config.log_all_hosts)
    logger = get_logger("train")
    logger.info("config: %s", config.to_json())
    logger.info("process %d/%d, %d devices", process_index, process_count,
                len(jax.devices()))
    # per-host contract, like the reference's SM_NUM_GPUS (train.py:50) —
    # so compare against this host's devices, not the global mesh
    n_local = len(jax.local_devices())
    if config.num_chips is not None and config.num_chips != n_local:
        logger.warning(
            "platform declared %d accelerators (TPU_NUM_CHIPS/SM_NUM_GPUS) "
            "but %d local JAX devices are visible; using the visible devices",
            config.num_chips, n_local)

    mesh = build_mesh(MeshConfig(dp=config.dp, fsdp=config.fsdp,
                                 ep=config.ep, pp=config.pp,
                                 tp=config.tp, sp=config.sp,
                                 dcn_dp=config.dcn_dp))
    logger.info("mesh: %s", dict(mesh.shape))

    # --- model + tokenizer (reference train.py:69,117) ---
    attention_impl = config.resolve_attention_impl(jax.devices()[0].platform)
    moe_overrides = {}
    if config.num_experts:
        moe_overrides = dict(num_experts=config.num_experts,
                             expert_top_k=config.expert_top_k,
                             moe_every=config.moe_every)
    if config.pp > 1:
        moe_overrides.update(
            pipeline_stages=config.pp,
            pipeline_microbatches=config.pipeline_microbatches)
    model, params, family, model_config = auto_models.from_pretrained(
        config.model_name_or_path,
        task=config.task,
        num_labels=config.num_labels,
        dtype=_DTYPES[config.dtype],
        param_dtype=_DTYPES[config.param_dtype],
        seed=config.seed,
        from_scratch=config.from_scratch,
        attention_impl=attention_impl,
        remat=config.remat,
        remat_policy=config.remat_policy,
        **moe_overrides,
    )
    if config.num_experts:
        logger.info("MoE: %d experts (top-%d) every %d layers, ep=%d",
                    config.num_experts, config.expert_top_k,
                    config.moe_every, config.ep)
    if attention_impl == "ring":
        if family == "t5":
            logger.info(
                "sp=%d: ring attention on the T5 encoder (relative bias "
                "re-tiled per ring step); decoder/cross attention run XLA "
                "with seq-sharded activations", config.sp)
        else:
            logger.info("sp=%d: ring attention selected", config.sp)
    if config.segment_packing:
        # only models that grew the segment_ids/position_ids kwargs can
        # consume packed batches — anything else would TypeError at
        # trace time with an opaque flax message
        if family not in ("gpt2", "bert"):
            raise ValueError(
                "--segment_packing needs a model wired for segment_ids/"
                "position_ids (gpt2 causal-lm, bert mlm); "
                f"got family {family!r}")
        if attention_impl == "ring":
            raise ValueError(
                "--segment_packing builds a [B,1,S,S] block-diagonal "
                "mask, which ring attention (sp>1) cannot shard over the "
                "seq axis — drop --sp or --segment_packing")
        if attention_impl == "flash":
            logger.warning(
                "--segment_packing builds a [B,1,S,S] block-diagonal "
                "mask, which the Pallas flash kernel treats as a general "
                "mask and falls back to XLA attention — long-sequence "
                "memory is O(S^2) on this run, not O(S)")
    tokenizer = load_tokenizer(config.model_name_or_path,
                               vocab_size=model_config.vocab_size)

    # --- data (reference train.py:72-100), per-host sharded, task-aware ---
    max_len = min(config.max_seq_length,
                  getattr(model_config, "max_position_embeddings",
                          config.max_seq_length))
    train_ds = build_dataset(config, tokenizer, "train", max_len,
                             config.max_train_samples, model_config)
    eval_ds = build_dataset(config, tokenizer, "test", max_len,
                            config.max_eval_samples, model_config)

    # Global batch = per-replica batch × data-parallel replicas (reference
    # semantics at train.py:143-144). tp/sp devices within a replica do
    # NOT multiply the batch — they cooperate on the same examples.
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
        data_parallel_size,
    )
    dp_size = data_parallel_size(mesh)
    global_train_batch = config.train_batch_size * dp_size
    global_eval_batch = config.eval_batch_size * dp_size
    buckets = config.bucket_sizes(max_len)
    if buckets:
        logger.info("length bucketing at widths %s", buckets)
    train_batcher = ShardedBatcher(train_ds, global_train_batch, mesh,
                                   shuffle=True, seed=config.seed,
                                   bucket_sizes=buckets)
    eval_batcher = ShardedBatcher(eval_ds, global_eval_batch, mesh,
                                  shuffle=False, drop_remainder=False,
                                  bucket_sizes=buckets)

    total_steps = train_batcher.steps_per_epoch() * config.epochs
    trainer = Trainer(config, model, params, mesh, total_steps=total_steps)

    # --- checkpoint/resume (capability the reference commented out) ---
    checkpointer = None
    start_epoch = 0
    start_step_in_epoch = 0
    if config.checkpoint_dir:
        checkpointer = Checkpointer(config.checkpoint_dir,
                                    max_to_keep=config.keep_checkpoints,
                                    async_save=config.async_checkpointing)
        if config.resume:
            restored = checkpointer.restore(trainer.state)
            if restored is not None:
                trainer.state, start_epoch, start_step_in_epoch = restored
                logger.info("resuming from epoch %d (step-in-epoch %d)",
                            start_epoch, start_step_in_epoch)
                if config.keep_best or config.early_stopping_patience:
                    logger.warning(
                        "--keep_best/--early_stopping_patience across a "
                        "resume: best-metric and patience tracking live "
                        "in host RAM, not the checkpoint — both restart "
                        "at this epoch (earlier epochs can no longer "
                        "win, and the patience budget is fresh)")

    results: dict = {}
    try:
        if config.do_train:
            logger.info("*** Train ***")
            history = trainer.fit(
                train_batcher, checkpointer=checkpointer,
                start_epoch=start_epoch,
                start_step_in_epoch=start_step_in_epoch,
                eval_batcher=eval_batcher if config.eval_each_epoch
                else None)
            if config.keep_best and trainer.best_epoch is not None:
                logger.info("exporting best epoch %d (%s = %.4f)",
                            trainer.best_epoch, config.best_metric,
                            trainer._best_metric)
            trainer.write_train_results(history)
            results["train"] = history

        if config.do_eval:
            logger.info("*** Evaluate ***")
            eval_results = trainer.evaluate(eval_batcher)
            if config.task == "seq2seq" and config.eval_rouge_samples:
                import numpy as np

                from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
                    generate,
                )
                from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
                    rouge_l,
                )

                n = min(config.eval_rouge_samples, len(eval_ds))
                cols = eval_ds[np.arange(n)]
                out = generate(model, trainer.export_params,
                               cols["input_ids"], cols["attention_mask"],
                               max_new_tokens=config.max_target_length)
                preds = [tokenizer.decode(r) for r in np.asarray(out)]
                refs = [tokenizer.decode(r[r != -100])
                        for r in cols["labels"]]
                eval_results.update(rouge_l(preds, refs))
            if config.task == "qa" and config.eval_qa_samples:
                # answer-TEXT exact-match/F1 (the metric SQuAD results are
                # quoted in), decoded from span logits via char offsets —
                # span-position accuracy alone under-reports whenever a
                # different token span yields the same normalized text
                import numpy as np

                from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
                    best_windowed_answers,
                    extract_answer_spans,
                    squad_em_f1,
                )

                questions, contexts, starts, answers = load_qa(
                    config.dataset, "test", dataset_path=config.dataset_path,
                    max_samples=config.eval_qa_samples, seed=config.seed)
                enc = tokenizer.encode_qa(questions, contexts, starts,
                                          answers, max_length=max_len,
                                          return_offsets=True,
                                          doc_stride=config.qa_doc_stride)
                # with doc-stride each input yields several window
                # features; predictions aggregate per example below
                ex_ids = enc["example_ids"]
                feat_ctx = np.asarray(contexts)[ex_ids]
                texts_scores: list = []
                bs = global_eval_batch
                n_feat = enc["input_ids"].shape[0]
                # hoisted: export_params re-merges LoRA adapters on every
                # read — do it once, not once per eval batch
                eval_params = trainer.export_params
                for lo in range(0, n_feat, bs):
                    sl = slice(lo, min(lo + bs, n_feat))
                    s_log, e_log = model.apply(
                        {"params": eval_params},
                        jnp.asarray(enc["input_ids"][sl]),
                        jnp.asarray(enc["attention_mask"][sl]),
                        token_type_ids=jnp.asarray(enc["token_type_ids"][sl])
                        if "token_type_ids" in enc else None,
                        deterministic=True)
                    texts_scores.extend(extract_answer_spans(
                        s_log, e_log, enc["offset_starts"][sl],
                        enc["offset_ends"][sl], feat_ctx[sl],
                        with_scores=True))
                preds = best_windowed_answers(
                    [t for t, _ in texts_scores],
                    [sc for _, sc in texts_scores], ex_ids, len(questions))
                em_f1 = squad_em_f1(preds, list(answers))
                eval_results["eval_exact_match"] = em_f1["exact_match"]
                eval_results["eval_f1"] = em_f1["f1"]
            trainer.write_eval_results(eval_results)
            results["eval"] = eval_results

        # --- terminal export, HF layout (reference train.py:182-183) ---
        auto_models.save_pretrained(config.model_dir, trainer.export_params,
                                    family, model_config)
        adapters = None
        if config.lora_rank > 0:
            adapters = trainer.state.params["lora"]
            if jax.process_count() > 1:
                # stacked (pipelined) adapters can shard across hosts —
                # gather collectively BEFORE the host-0 gate, same
                # discipline as save_pretrained
                from jax.experimental import multihost_utils

                adapters = multihost_utils.process_allgather(adapters,
                                                             tiled=True)
        if jax.process_index() == 0:
            tokenizer.save_pretrained(config.model_dir)
            if adapters is not None:
                # adapter sidecar next to the merged export: deployment
                # can ship megabytes instead of the full model
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
                    save_adapters,
                )
                save_adapters(
                    os.path.join(config.model_dir, "adapter"),
                    adapters, rank=config.lora_rank,
                    alpha=config.lora_alpha, targets=config.lora_targets)
    finally:
        # commits any in-flight ASYNC checkpoint write even when fit/eval
        # raise — a crash after "save started" must not lose the checkpoint
        if checkpointer is not None:
            checkpointer.close()
    return results


if __name__ == "__main__":
    # real CLI runs default their telemetry into the output dir (the
    # <out_dir>/telemetry/{events.jsonl,trace.json} layout, README
    # "Telemetry"); in-process callers (tests) opt in via
    # HSTD_TELEMETRY_DIR or obs.configure instead, so importing/calling
    # main() never writes files as a side effect
    if not os.environ.get("HSTD_TELEMETRY_DIR", "").strip():
        from huggingface_sagemaker_tensorflow_distributed_tpu import obs

        _out = os.environ.get("TPU_OUTPUT_DATA_DIR",
                              os.environ.get("SM_OUTPUT_DATA_DIR", ""))
        if _out:
            obs.configure(out_dir=os.path.join(_out, "telemetry"))
    main(sys.argv[1:])
