// WordPiece tokenizer core — C++ native runtime component.
//
// TPU-native framework equivalent of the Rust `tokenizers` library the
// reference consumes via AutoTokenizer (reference scripts/train.py:69,75,90;
// SURVEY.md component D8). The hot path — per-character basic tokenization
// (cleanup, lowercasing, accent folding, punctuation/CJK splitting) followed
// by greedy longest-match WordPiece — runs here, multithreaded over rows;
// batch assembly (specials, truncation, padding to static [N, L]) stays in
// numpy on the Python side (data/wordpiece.py) where it is cheap and shared
// with the pure-Python fallback implementation.
//
// API surface (C, for ctypes): build a tokenizer from a newline-separated
// vocab, then tokenize batches of UTF-8 texts into per-row token streams:
// ids + word index + code-point offsets. Semantics match HF BertTokenizer
// (do_basic_tokenize=True): see tests/test_wordpiece.py for the parity
// suite against both the Python twin and HF's implementation.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// UTF-8 + Unicode tables
// ---------------------------------------------------------------------------

// Decode one UTF-8 code point at s[i]; advances i. Invalid bytes decode as
// U+FFFD and advance by one (matches Python's surrogateescape-free reading
// of already-valid str data; invalid input only arises from foreign bytes).
inline uint32_t decode_utf8(const unsigned char* s, size_t len, size_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) { i += 1; return c; }
  if ((c >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6) | (s[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12) |
                  ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1; return 0xFFFD;
}

inline void encode_utf8(uint32_t cp, std::string& out) {
  if (cp < 0x80) { out.push_back((char)cp); }
  else if (cp < 0x800) {
    out.push_back((char)(0xC0 | (cp >> 6)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back((char)(0xE0 | (cp >> 12)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    out.push_back((char)(0xF0 | (cp >> 18)));
    out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  }
}

inline bool is_whitespace(uint32_t cp) {
  // HF _is_whitespace: \t \n \r space + Zs category.
  if (cp == ' ' || cp == '\t' || cp == '\n' || cp == '\r') return true;
  switch (cp) {
    case 0x00A0: case 0x1680: case 0x202F: case 0x205F: case 0x3000: return true;
    default: return cp >= 0x2000 && cp <= 0x200A;
  }
}

inline bool is_control(uint32_t cp) {
  // HF _is_control: C* categories except \t \n \r (those are whitespace).
  if (cp == '\t' || cp == '\n' || cp == '\r') return false;
  if (cp < 0x20) return true;
  if (cp >= 0x7F && cp <= 0x9F) return true;
  // Cf (format) chars — full category (Unicode 15), so the Python twin
  // (unicodedata-based) and this core agree on every input.
  if (cp == 0x00AD || cp == 0x061C || cp == 0x06DD || cp == 0x070F ||
      cp == 0x08E2 || cp == 0x180E || cp == 0xFEFF || cp == 0x110BD ||
      cp == 0x110CD)
    return true;
  if (cp >= 0x0600 && cp <= 0x0605) return true;
  if (cp >= 0x0890 && cp <= 0x0891) return true;
  if (cp >= 0x200B && cp <= 0x200F) return true;
  if (cp >= 0x202A && cp <= 0x202E) return true;
  if (cp >= 0x2060 && cp <= 0x2064) return true;
  if (cp >= 0x2066 && cp <= 0x206F) return true;
  if (cp >= 0xFFF9 && cp <= 0xFFFB) return true;
  if (cp >= 0x13430 && cp <= 0x1343F) return true;
  if (cp >= 0x1BCA0 && cp <= 0x1BCA3) return true;
  if (cp >= 0x1D173 && cp <= 0x1D17A) return true;
  if (cp == 0xE0001 || (cp >= 0xE0020 && cp <= 0xE007F)) return true;
  return false;
}

inline bool is_punctuation(uint32_t cp) {
  // HF _is_punctuation: the four ASCII ranges (which include $ + < = > ^ ` | ~,
  // i.e. some S-category chars) plus Unicode P*. P* is approximated by the
  // blocks that occur in practice; the ASCII ranges are exact.
  if ((cp >= 33 && cp <= 47) || (cp >= 58 && cp <= 64) ||
      (cp >= 91 && cp <= 96) || (cp >= 123 && cp <= 126))
    return true;
  if (cp >= 0x2010 && cp <= 0x2027) return true;   // hyphens, quotes, daggers
  if (cp >= 0x2030 && cp <= 0x205E) return true;   // per-mille ... punctuation
  if (cp >= 0x3001 && cp <= 0x3003) return true;   // CJK comma/stop
  if (cp >= 0x3008 && cp <= 0x3011) return true;   // CJK brackets
  if (cp == 0x3014 || cp == 0x3015 || cp == 0x301C) return true;
  if (cp >= 0xFF01 && cp <= 0xFF0F) return true;   // fullwidth ! ... /
  if (cp >= 0xFF1A && cp <= 0xFF20) return true;   // fullwidth : ... @
  if (cp >= 0xFF3B && cp <= 0xFF40) return true;
  if (cp >= 0xFF5B && cp <= 0xFF65) return true;
  if (cp == 0x00A1 || cp == 0x00A7 || cp == 0x00AB || cp == 0x00B6 ||
      cp == 0x00B7 || cp == 0x00BB || cp == 0x00BF)
    return true;
  return false;
}

inline bool is_cjk(uint32_t cp) {
  // HF _is_chinese_char ranges (BasicTokenizer._tokenize_chinese_chars).
  return (cp >= 0x4E00 && cp <= 0x9FFF) || (cp >= 0x3400 && cp <= 0x4DBF) ||
         (cp >= 0x20000 && cp <= 0x2A6DF) || (cp >= 0x2A700 && cp <= 0x2B73F) ||
         (cp >= 0x2B740 && cp <= 0x2B81F) || (cp >= 0x2B820 && cp <= 0x2CEAF) ||
         (cp >= 0xF900 && cp <= 0xFAFF) || (cp >= 0x2F800 && cp <= 0x2FA1F);
}

inline bool is_combining_mark(uint32_t cp) {
  // Mn category approximation: combining diacritics blocks. After the
  // accent fold below, these are what NFD normalization would leave.
  return (cp >= 0x0300 && cp <= 0x036F) || (cp >= 0x1AB0 && cp <= 0x1AFF) ||
         (cp >= 0x1DC0 && cp <= 0x1DFF) || (cp >= 0x20D0 && cp <= 0x20FF) ||
         (cp >= 0xFE20 && cp <= 0xFE2F);
}

// Lowercase a code point (str.lower() for the scripts BERT vocabs cover:
// ASCII, Latin-1, Latin Extended-A, Greek, Cyrillic).
inline uint32_t to_lower(uint32_t cp) {
  if (cp >= 'A' && cp <= 'Z') return cp + 0x20;
  if (cp >= 0x00C0 && cp <= 0x00DE && cp != 0x00D7) return cp + 0x20;
  // İ (U+0130) lowercases to i + combining-dot (which NFD strips): NOT
  // to dotless ı — the cp|1 pairing below would silently produce ı and
  // break parity with Python's 'İ'.lower() + strip-Mn
  if (cp == 0x0130) return 'i';
  if (cp >= 0x0100 && cp <= 0x0137) return (cp | 1);
  if (cp >= 0x0139 && cp <= 0x0148) return ((cp - 1) | 1) + 1;
  if (cp >= 0x014A && cp <= 0x0177) return (cp | 1);
  if (cp == 0x0178) return 0x00FF;
  if (cp >= 0x0179 && cp <= 0x017E) return ((cp - 1) | 1) + 1;
  if (cp >= 0x0391 && cp <= 0x03A9 && cp != 0x03A2) return cp + 0x20;
  if (cp >= 0x0410 && cp <= 0x042F) return cp + 0x20;
  if (cp >= 0x0400 && cp <= 0x040F) return cp + 0x50;
  return cp;
}

// Strip accent: NFD-decompose-and-drop-Mn, folded into a single table for
// the Latin ranges (é→e, ñ→n, ç→c, ř→r, ...). Returns the base letter, or
// the input unchanged. Applied after lowercasing, so only lowercase forms
// need entries.
inline uint32_t fold_accent(uint32_t cp) {
  if (cp < 0x00C0) return cp;
  // Latin-1 supplement lowercase
  if (cp >= 0x00E0 && cp <= 0x00E5) return 'a';
  if (cp == 0x00E7) return 'c';
  if (cp >= 0x00E8 && cp <= 0x00EB) return 'e';
  if (cp >= 0x00EC && cp <= 0x00EF) return 'i';
  if (cp == 0x00F1) return 'n';
  if (cp >= 0x00F2 && cp <= 0x00F6) return 'o';
  if (cp >= 0x00F9 && cp <= 0x00FC) return 'u';
  if (cp == 0x00FD || cp == 0x00FF) return 'y';
  // Latin Extended-A lowercase (odd code points pair with base letters)
  if (cp >= 0x0100 && cp <= 0x0105) return 'a';
  if (cp >= 0x0106 && cp <= 0x010D) return 'c';
  // Ranges keep ONLY code points with a canonical NFD decomposition —
  // stroke/bar/eng/dotless letters (Đđ Ħħ ı ĸ Ŀŀ Łł ŉ Ŋŋ Ŧŧ) do not
  // decompose, so HF's NFD+strip-Mn (and our Python twin) keep them;
  // folding them here would break the C++/Python/HF parity contract.
  if (cp >= 0x010E && cp <= 0x010F) return 'd';
  if (cp >= 0x0112 && cp <= 0x011B) return 'e';
  if (cp >= 0x011C && cp <= 0x0123) return 'g';
  if (cp >= 0x0124 && cp <= 0x0125) return 'h';
  if (cp >= 0x0128 && cp <= 0x012F) return 'i';  // 0x130 handled in to_lower
  if (cp >= 0x0134 && cp <= 0x0135) return 'j';
  if (cp >= 0x0136 && cp <= 0x0137) return 'k';
  if (cp >= 0x0139 && cp <= 0x013E) return 'l';
  if (cp >= 0x0143 && cp <= 0x0148) return 'n';
  if (cp >= 0x014C && cp <= 0x0151) return 'o';
  if (cp >= 0x0154 && cp <= 0x0159) return 'r';
  if (cp >= 0x015A && cp <= 0x0161) return 's';
  if (cp >= 0x0162 && cp <= 0x0165) return 't';
  if (cp >= 0x0168 && cp <= 0x0173) return 'u';
  if (cp >= 0x0174 && cp <= 0x0175) return 'w';
  if (cp >= 0x0176 && cp <= 0x0177) return 'y';
  if (cp >= 0x0179 && cp <= 0x017E) return 'z';
  return cp;
}

// ---------------------------------------------------------------------------
// Tokenizer
// ---------------------------------------------------------------------------

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  bool lowercase;
  int32_t unk_id;
  size_t max_word_chars = 100;  // HF max_input_chars_per_word
};

struct Word {
  std::string text;   // cleaned (lowercased/folded) word text
  int32_t start, end; // code-point offsets into the ORIGINAL input text
  int32_t word_index; // index of the source whitespace-word
};

// Basic tokenization: clean + lowercase/fold + split whitespace, then split
// punctuation / CJK into standalone words. Offsets are code-point positions
// in the raw input (for QA span mapping, HF offset_mapping semantics).
void basic_tokenize(const Tokenizer& tok, const unsigned char* text, size_t len,
                    std::vector<Word>& words) {
  std::string cur;
  int32_t cur_start = -1;
  int32_t word_index = -1;      // index of current whitespace-delimited word
  bool in_space = true;         // are we between whitespace-words?
  int32_t cp_index = 0;         // code-point position in the original text
  int32_t last_cp = 0;

  auto flush = [&](int32_t end_cp) {
    if (!cur.empty()) {
      words.push_back({cur, cur_start, end_cp, word_index});
      cur.clear();
    }
    cur_start = -1;
  };

  for (size_t i = 0; i < len;) {
    uint32_t cp = decode_utf8(text, len, i);
    int32_t pos = cp_index++;
    if (cp == 0 || cp == 0xFFFD || is_control(cp)) continue;
    if (is_whitespace(cp)) {
      flush(pos);
      in_space = true;
      continue;
    }
    if (in_space) { word_index++; in_space = false; }
    if (tok.lowercase) {
      cp = fold_accent(to_lower(cp));
      if (is_combining_mark(cp)) continue;  // NFD residue: drop
    }
    if (is_punctuation(cp) || is_cjk(cp)) {
      flush(pos);
      std::string s;
      encode_utf8(cp, s);
      words.push_back({s, pos, pos + 1, word_index});
      continue;
    }
    if (cur.empty()) cur_start = pos;
    encode_utf8(cp, cur);
    last_cp = pos;
    (void)last_cp;
  }
  flush(cp_index);
}

// Greedy longest-match WordPiece over one basic word. Emits (id, start, end,
// word_index) tuples; a word with no match emits a single UNK spanning it.
// Offsets of sub-pieces are char positions within the CLEANED word mapped
// back proportionally — exact per-piece raw offsets are not recoverable
// after folding, so pieces share the word's [start, end) like HF's slow
// tokenizer unless chars map 1:1 (the common ASCII case, handled exactly).
template <typename Emit>
void wordpiece(const Tokenizer& tok, const Word& w, Emit emit) {
  // count code points + record byte offset of each code point in w.text
  std::vector<size_t> cp_byte;  // byte index of each code point
  const unsigned char* s = (const unsigned char*)w.text.data();
  size_t blen = w.text.size();
  for (size_t i = 0; i < blen;) {
    cp_byte.push_back(i);
    decode_utf8(s, blen, i);
  }
  size_t n_cp = cp_byte.size();
  cp_byte.push_back(blen);
  if (n_cp > tok.max_word_chars) {
    emit(tok.unk_id, w.start, w.end, w.word_index);
    return;
  }
  // 1:1 raw-offset mapping only valid when cleaned length == raw span length
  bool exact = (int32_t)n_cp == (w.end - w.start);

  size_t start = 0;
  std::vector<std::tuple<int32_t, size_t, size_t>> pieces;  // id, cp_start, cp_end
  while (start < n_cp) {
    size_t end = n_cp;
    int32_t found = -1;
    std::string probe;
    while (end > start) {
      probe.assign(start == 0 ? "" : "##");
      probe.append(w.text, cp_byte[start], cp_byte[end] - cp_byte[start]);
      auto it = tok.vocab.find(probe);
      if (it != tok.vocab.end()) { found = it->second; break; }
      end--;
    }
    if (found < 0) {
      emit(tok.unk_id, w.start, w.end, w.word_index);
      return;
    }
    pieces.emplace_back(found, start, end);
    start = end;
  }
  for (auto& [id, s_cp, e_cp] : pieces) {
    int32_t rs = exact ? w.start + (int32_t)s_cp : w.start;
    int32_t re = exact ? w.start + (int32_t)e_cp : w.end;
    emit(id, rs, re, w.word_index);
  }
}

void tokenize_one(const Tokenizer& tok, const unsigned char* text, size_t len,
                  int32_t cap, int32_t* ids, int32_t* word_ids,
                  int32_t* starts, int32_t* ends, int32_t* count) {
  std::vector<Word> words;
  words.reserve(len / 4 + 4);
  basic_tokenize(tok, text, len, words);
  int32_t n = 0;
  for (const Word& w : words) {
    if (n >= cap) break;
    wordpiece(tok, w, [&](int32_t id, int32_t s, int32_t e, int32_t wi) {
      if (n >= cap) return;
      ids[n] = id;
      if (word_ids) word_ids[n] = wi;
      if (starts) starts[n] = s;
      if (ends) ends[n] = e;
      n++;
    });
  }
  *count = n;
}

}  // namespace

extern "C" {

// vocab: newline-separated token strings; token id = line index.
void* wp_new(const char* vocab_bytes, int64_t vocab_len, int lowercase,
             int32_t unk_id) {
  auto* t = new Tokenizer();
  t->lowercase = lowercase != 0;
  t->unk_id = unk_id;
  const char* p = vocab_bytes;
  const char* end = vocab_bytes + vocab_len;
  int32_t id = 0;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    size_t n = nl ? (size_t)(nl - p) : (size_t)(end - p);
    if (n > 0 && p[n - 1] == '\r') n--;
    t->vocab.emplace(std::string(p, n), id++);
    if (!nl) break;
    p = nl + 1;
  }
  return t;
}

void wp_free(void* t) { delete (Tokenizer*)t; }

int32_t wp_vocab_size(void* t) { return (int32_t)((Tokenizer*)t)->vocab.size(); }

int32_t wp_token_id(void* t, const char* token) {
  auto& v = ((Tokenizer*)t)->vocab;
  auto it = v.find(token);
  return it == v.end() ? -1 : it->second;
}

// Tokenize n texts (concatenated UTF-8 `texts`, row r = bytes
// [offsets[r], offsets[r+1])) into per-row streams of at most `cap` tokens.
// Outputs are [n, cap] row-major; counts is [n]. word_ids/starts/ends may be
// NULL. Multithreaded over rows.
void wp_tokenize_batch(void* tptr, const char* texts, const int64_t* offsets,
                       int32_t n, int32_t cap, int32_t n_threads,
                       int32_t* ids, int32_t* word_ids,
                       int32_t* starts, int32_t* ends, int32_t* counts) {
  const Tokenizer& tok = *(Tokenizer*)tptr;
  n_threads = std::max(1, std::min<int32_t>(n_threads, n));
  std::atomic<int32_t> next(0);
  auto work = [&]() {
    for (;;) {
      int32_t r = next.fetch_add(1);
      if (r >= n) return;
      const unsigned char* p = (const unsigned char*)texts + offsets[r];
      size_t len = (size_t)(offsets[r + 1] - offsets[r]);
      tokenize_one(tok, p, len, cap,
                   ids + (int64_t)r * cap,
                   word_ids ? word_ids + (int64_t)r * cap : nullptr,
                   starts ? starts + (int64_t)r * cap : nullptr,
                   ends ? ends + (int64_t)r * cap : nullptr,
                   counts + r);
    }
  };
  if (n_threads == 1) { work(); return; }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t i = 0; i < n_threads; i++) threads.emplace_back(work);
  for (auto& th : threads) th.join();
}

}  // extern "C"
