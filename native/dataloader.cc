// Data-loader core — C++ native runtime component.
//
// TPU-native framework equivalent of the native machinery under the
// reference's data path: Apache Arrow's C++ column store behind HF
// `datasets` (reference scripts/train.py:72) and tf.data's C++ batching
// iterator (reference scripts/train.py:84-86,98; SURVEY.md D9/D10).
// Three primitives, all operating on host int32 column arrays:
//
//  - dl_permutation: deterministic keyed-hash shuffle (splitmix64 keys,
//    stable sort) — the epoch-order agreement every host computes
//    identically, the input-pipeline analogue of the reference's rank-0
//    broadcast discipline. Key-sorted rather than Fisher-Yates so the
//    Python twin is a vectorized numpy argsort producing bit-identical
//    orders (data/native.py::_py_permutation).
//  - dl_gather: parallel row gather of a batch's indices into a contiguous
//    output buffer (the from_tensor_slices→batch step, done zero-copy into
//    a caller-owned staging buffer that jax can ingest directly).
//  - dl_row_lengths: token count per row (length-bucketed batching support).
//
// Python binding: data/native.py (ctypes).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

inline uint64_t mix64(uint64_t z) {
  z += 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// out[0..n) = seeded permutation of [0, n): indices stably sorted by a
// per-index splitmix64 key. Same (n, seed) -> same result on every host
// and platform; mirrored exactly (vectorized) in data/native.py.
void dl_permutation(int64_t n, uint64_t seed, int64_t* out) {
  uint64_t seedmix = seed * 0xD1342543DE82EF95ull + 0x2545F4914F6CDD1Dull;
  std::vector<uint64_t> keys((size_t)n);
  for (int64_t i = 0; i < n; i++)
    keys[i] = mix64(seedmix ^ ((uint64_t)i * 0x9E3779B97F4A7C15ull));
  for (int64_t i = 0; i < n; i++) out[i] = i;
  std::stable_sort(out, out + n, [&](int64_t a, int64_t b) {
    return keys[a] < keys[b];
  });
}

// Gather rows: out[b, :] = src[idx[b], :], row_elems int32 elements per row.
// Parallel memcpy over batch rows.
void dl_gather(const int32_t* src, int64_t row_elems, const int64_t* idx,
               int64_t n_idx, int32_t* out, int32_t n_threads) {
  const size_t row_bytes = (size_t)row_elems * sizeof(int32_t);
  auto copy_range = [&](int64_t lo, int64_t hi) {
    for (int64_t b = lo; b < hi; b++)
      memcpy(out + b * row_elems, src + idx[b] * row_elems, row_bytes);
  };
  if (n_threads <= 1 || n_idx < 256) { copy_range(0, n_idx); return; }
  n_threads = std::min<int64_t>(n_threads, n_idx);
  std::vector<std::thread> threads;
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(lo + chunk, n_idx);
    if (lo >= hi) break;
    threads.emplace_back(copy_range, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// lengths[r] = number of nonzero entries in mask row r (token count);
// used for length-bucketed batching.
void dl_row_lengths(const int32_t* mask, int64_t n_rows, int64_t row_elems,
                    int32_t* lengths, int32_t n_threads) {
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      const int32_t* row = mask + r * row_elems;
      int32_t c = 0;
      for (int64_t j = 0; j < row_elems; j++) c += (row[j] != 0);
      lengths[r] = c;
    }
  };
  if (n_threads <= 1 || n_rows < 1024) { work(0, n_rows); return; }
  n_threads = std::min<int64_t>(n_threads, n_rows);
  std::vector<std::thread> threads;
  int64_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(lo + chunk, n_rows);
    if (lo >= hi) break;
    threads.emplace_back(work, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// Newline index of a text/jsonl corpus: parallel memchr scan with pread
// (no shared file position), used by the streaming tier's LineCorpus to
// build its line-offset index at disk bandwidth instead of a Python
// line loop. Returns the newline count; when out != null, fills up to
// cap sorted byte positions (a caller seeing count > cap re-calls with
// an exact buffer — one scan in the common generous-guess case).
// Returns -1 when the file cannot be opened/stat'd.
int64_t dl_line_index(const char* path, int64_t* out, int64_t cap,
                      int32_t n_threads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return -1; }
  const int64_t size = (int64_t)st.st_size;
  if (size == 0) { close(fd); return 0; }
  if (n_threads < 1) n_threads = 1;
  n_threads = (int32_t)std::min<int64_t>(n_threads, (size + (1 << 20) - 1) >> 20);
  if (n_threads < 1) n_threads = 1;
  std::vector<std::vector<int64_t>> found((size_t)n_threads);
  std::atomic<bool> io_error{false};
  int64_t chunk = (size + n_threads - 1) / n_threads;
  auto scan = [&](int32_t t) {
    int64_t lo = (int64_t)t * chunk, hi = std::min<int64_t>(lo + chunk, size);
    std::vector<char> buf((size_t)std::min<int64_t>(hi - lo, 4 << 20));
    int64_t pos = lo;
    while (pos < hi) {
      int64_t want = std::min<int64_t>((int64_t)buf.size(), hi - pos);
      int64_t got = pread(fd, buf.data(), (size_t)want, (off_t)pos);
      if (got <= 0) { io_error.store(true); return; }
      const char* p = buf.data();
      const char* end = p + got;
      while ((p = (const char*)memchr(p, '\n', (size_t)(end - p)))) {
        found[(size_t)t].push_back(pos + (p - buf.data()));
        p++;
      }
      pos += got;
    }
  };
  if (n_threads == 1) {
    scan(0);
  } else {
    std::vector<std::thread> threads;
    for (int32_t t = 0; t < n_threads; t++) threads.emplace_back(scan, t);
    for (auto& th : threads) th.join();
  }
  close(fd);
  if (io_error.load()) return -1;
  int64_t total = 0;
  for (auto& v : found) total += (int64_t)v.size();
  if (out) {
    int64_t k = 0;
    for (auto& v : found) {             // threads cover ascending ranges
      for (int64_t p : v) {
        if (k >= cap) break;
        out[k++] = p;
      }
      if (k >= cap) break;
    }
  }
  return total;
}

}  // extern "C"
