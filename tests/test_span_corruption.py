"""T5 span-corruption pretraining builder: paper-layout structure,
lossless reconstruction, corruption-rate statistics, e2e training."""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

VOCAB = 1024
EOS = 1


def _build(texts, **kw):
    tok = WordHashTokenizer(vocab_size=VOCAB)
    base = dict(max_source_length=64, max_target_length=32,
                eos_token_id=EOS, seed=0)
    base.update(kw)
    return tok, ArrayDataset.from_span_corruption_texts(tok, texts, **base)


def _sentinel_range(n=100):
    return set(range(VOCAB - n, VOCAB))


def _safe_words(tok, n):
    """Words whose hash buckets stay clear of the sentinel range (a real
    T5 vocab RESERVES its top ids for <extra_id_*>; the hash tier
    doesn't, so the test corpus must avoid collisions)."""
    words = []
    i = 0
    while len(words) < n:
        w = f"w{i}"
        if tok._word_id(w) < VOCAB - 120:
            words.append(w)
        i += 1
    return words


def test_structure_and_reconstruction():
    """Splicing target spans back into the source sentinels reproduces
    the original token stream exactly — corruption is lossless."""
    tok0 = WordHashTokenizer(vocab_size=VOCAB)
    texts = [" ".join(_safe_words(tok0, 12))] * 8
    tok, ds = _build(texts)
    clean = tok(texts, max_length=64, add_special_tokens=False)
    for r in range(len(texts)):
        src = ds.columns["input_ids"][r][ds.columns["attention_mask"][r] > 0]
        tgt = ds.columns["labels"][r]
        tgt = tgt[tgt != -100]
        assert tgt[-1] == EOS
        # parse target: sentinel -> following tokens are that span
        spans = {}
        cur = None
        for t in tgt[:-1]:
            if int(t) in _sentinel_range():
                cur = int(t)
                spans[cur] = []
            else:
                spans[cur].append(int(t))
        # the final sentinel opens an empty span
        finals = [s for s, v in spans.items() if not v]
        assert len(finals) == 1 and finals[0] == min(spans)
        assert src[-1] == EOS          # T5 inputs end with </s>
        rebuilt = []
        for t in src[:-1]:
            if int(t) in _sentinel_range():
                rebuilt += spans[int(t)]
            else:
                rebuilt.append(int(t))
        want = clean["input_ids"][r][clean["attention_mask"][r] > 0]
        np.testing.assert_array_equal(rebuilt, want)
        # sentinels appear in descending order in the source
        sents = [int(t) for t in src if int(t) in _sentinel_range()]
        assert sents == sorted(sents, reverse=True)


def test_corruption_rate():
    tok0 = WordHashTokenizer(vocab_size=VOCAB)
    texts = [" ".join(_safe_words(tok0, 60))] * 20
    tok, ds = _build(texts, corruption_rate=0.15)
    clean = tok(texts, max_length=64, add_special_tokens=False)
    n_clean = clean["attention_mask"].sum()
    dropped = 0
    for r in range(len(texts)):
        tgt = ds.columns["labels"][r]
        tgt = tgt[tgt != -100]
        dropped += sum(1 for t in tgt[:-1] if int(t) not in _sentinel_range())
    assert 0.08 < dropped / n_clean < 0.25


def test_tiny_rows_survive():
    tok, ds = _build(["hi", "a b", ""])
    assert ds.columns["input_ids"].shape[0] == 3
    # extreme corruption rates partition without crashing
    _build([" ".join(_safe_words(WordHashTokenizer(vocab_size=VOCAB), 10))],
           corruption_rate=0.8)
    # degenerate rows still have a valid (EOS-only) target
    assert (ds.columns["decoder_attention_mask"].sum(1) >= 1).all()


def test_t5_trains_on_span_corruption(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
    )

    texts, _ = synthetic_text_classification(48, seed=0)
    tok = WordHashTokenizer(vocab_size=256)
    ds = ArrayDataset.from_span_corruption_texts(
        tok, texts, max_source_length=24, max_target_length=16,
        eos_token_id=1, seed=0)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    cfg = T5Config(vocab_size=256, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_decoder_layers=2, num_heads=4,
                   dropout_rate=0.0)
    model = T5ForConditionalGeneration(cfg)
    params = init_params(model, cfg)
    tc = TrainConfig(task="seq2seq", dtype="float32", learning_rate=5e-3,
                     scale_lr_by_world_size=False, log_every_steps=0,
                     rng_impl="threefry", epochs=3)
    trainer = Trainer(tc, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.9
