"""Cross-engine KV block-set transport (ISSUE 18): migration must
change WHERE a request decodes, never WHAT it emits — a resident moved
mid-decode (across a gather-bucket boundary, greedy or sampled) resumes
on the destination token-exactly with zero re-prefill; a randomized
two-engine submit/step/migrate schedule conserves every block on BOTH
pools at every step; ``Router.drain`` live-migrates residents so a
drain completes without waiting anything out; and the disaggregated
prefill/decode fleet keeps strict role separation while staying
token-identical to one engine.
"""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
    Router,
    parse_roles,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.transport import (
    TransportError,
    can_accept,
    migrate_request,
    pool_signature,
)


@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


_KW = dict(num_slots=2, block_size=4, num_blocks=40, prefill_chunk=8,
           max_model_len=64, gather_buckets=[16, 32])


def _engine(model, params, **over):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    kw = dict(_KW)
    kw.update(over)
    return ServeEngine(model, params, **kw)


def _slot_of(eng, rid):
    return next((s for s in eng.sched.slots
                 if s.request is not None and s.request.rid == rid), None)


def _conserved(eng):
    b = eng.blocks
    return (b.num_free + b.num_used + b.num_cached + b.num_hosted
            == b.num_blocks - 1)


def _baseline(model, params, trace, **over):
    eng = _engine(model, params, **over)
    reqs = [eng.submit(p, m, **kw) for p, m, kw in trace]
    eng.run()
    return [list(eng.output_ids(r)) for r in reqs]


def test_migrate_mid_decode_across_bucket_boundary_token_exact(
        gpt2_setup):
    """The core exactness contract: a request migrated MID-DECODE —
    after its context crossed the first gather bucket (16), so the
    destination resumes in the wider bucket — emits exactly the tokens
    an unmigrated engine emits, with zero re-prefill on the
    destination (its prefill counters stay at 0)."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 120, (14,)).astype(np.int32)
    base = _baseline(model, params, [(prompt, 12, {})])

    src = _engine(model, params)
    dst = _engine(model, params)
    req = src.submit(prompt, 12)
    while src.has_work():
        slot = _slot_of(src, req.rid)
        if slot is not None and slot.context_len > 18:
            break
        src.step()
    assert _slot_of(src, req.rid).context_len > 16   # bucket crossed
    info = migrate_request(src, dst, req.rid)
    assert info is not None and not info["cold"]
    assert info["bytes"] > 0 and info["context_len"] > 16
    # source fully released, destination fully owns the request
    assert _slot_of(src, req.rid) is None
    assert not src.has_work()
    assert src.blocks.num_used == 0 and _conserved(src)
    dst.run()
    assert list(dst.output_ids(req)) == base[0]
    assert req.rid in dst.finished and req.rid not in src.finished
    assert dst.stats().prefill_chunks == 0           # zero re-prefill
    assert dst.stats().migrations_in == 1
    assert src.stats().migrations_out == 1
    assert _conserved(dst) and dst.blocks.num_used == 0


def test_migrate_sampled_stream_bitwise_identical(gpt2_setup):
    """Sampled exactness: token n's key folds (request seed, n) — a
    pure function migration cannot perturb — so the migrated stream is
    BITWISE the unmigrated one."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 120, (9,)).astype(np.int32)
    skw = dict(temperature=0.9, top_k=20, seed=13)
    base = _baseline(model, params, [(prompt, 10, skw)])

    src = _engine(model, params)
    dst = _engine(model, params)
    req = src.submit(prompt, 10, **skw)
    while src.has_work() and len(req.output) < 4:
        src.step()
    assert len(req.output) >= 1                      # mid-decode
    assert migrate_request(src, dst, req.rid) is not None
    dst.run()
    assert list(dst.output_ids(req)) == base[0]


def test_migrate_rejections_and_signature(gpt2_setup):
    """The transport refuses loudly instead of corrupting state:
    self-moves, unknown rids, and geometry-incompatible pools (the
    block-set signature check) are all errors; an over-small
    destination fails ``can_accept``."""
    _cfg, model, params = gpt2_setup
    src = _engine(model, params)
    dst = _engine(model, params)
    assert pool_signature(src) == pool_signature(dst)
    req = src.submit(np.arange(1, 9, dtype=np.int32), 4)
    with pytest.raises(TransportError):
        migrate_request(src, src, req.rid)
    with pytest.raises(TransportError):
        migrate_request(src, dst, 10 ** 9)           # never submitted
    # different block_size => different pool geometry => refused
    other = _engine(model, params, block_size=8, num_blocks=20)
    assert pool_signature(src) != pool_signature(other)
    with pytest.raises(TransportError):
        migrate_request(src, other, req.rid)
    # a destination too small for the request's worst case
    tiny = _engine(model, params, num_blocks=4)
    assert not can_accept(tiny, req)
    with pytest.raises(TransportError):
        migrate_request(src, tiny, req.rid)
    src.run()
    # a finished request is a no-op, not an error
    assert migrate_request(src, dst, req.rid) is None


def test_randomized_two_engine_conservation_schedule(gpt2_setup):
    """The ISSUE 18 conservation property: 300 random
    submit/step/migrate operations across two engines (tight pools, so
    preemption pressure arises naturally) keep EVERY step's block
    accounting exact on BOTH pools (free + used + cached + hosted ==
    allocatable), every slot table points into its own pool, every
    request finishes exactly once somewhere, and the final outputs are
    token-identical to a single-engine run of the same trace."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(7)
    kw = dict(num_blocks=14)
    engines = [_engine(model, params, **kw), _engine(model, params, **kw)]
    trace, reqs, homes = [], [], []
    migrations = refusals = 0
    for _ in range(300):
        op = rng.rand()
        if op < 0.35 and len(reqs) < 20:
            p = rng.randint(1, 120, (int(rng.randint(4, 12)),))
            m = int(rng.randint(2, 9))
            e = int(rng.randint(2))
            trace.append((p.astype(np.int32), m, {}))
            reqs.append(engines[e].submit(p.astype(np.int32), m))
            homes.append(e)
        elif op < 0.55:
            # migrate a random live resident to the other engine
            e = int(rng.randint(2))
            resident = [s.request.rid for s in engines[e].sched.slots
                        if s.request is not None]
            if resident:
                rid = int(rng.choice(resident))
                try:
                    if migrate_request(engines[e], engines[1 - e],
                                       rid) is not None:
                        migrations += 1
                        homes[[q.rid for q in reqs].index(rid)] = 1 - e
                except TransportError:
                    refusals += 1    # e.g. destination worst-case full
        else:
            e = int(rng.randint(2))
            if engines[e].has_work():
                engines[e].step()
        for eng in engines:
            assert _conserved(eng)
            for s in eng.sched.slots:
                if s.request is not None:
                    n = eng.blocks.blocks_for(s.context_len)
                    assert all(0 < int(b) < eng.blocks.num_blocks
                               for b in s.table[:n])
    for eng in engines:
        eng.run()
    assert migrations > 0
    finished = [set(e.finished) for e in engines]
    assert not (finished[0] & finished[1])           # exactly-once
    assert finished[0] | finished[1] == {q.rid for q in reqs}
    base = _baseline(model, params, trace, **kw)
    outs = [list(engines[homes[i]].output_ids(q))
            for i, q in enumerate(reqs)]
    assert outs == base
    for eng in engines:
        assert eng.blocks.num_used == 0 and _conserved(eng)


def test_drain_live_migrates_residents_and_completes(gpt2_setup,
                                                     tmp_path):
    """With transport under it, ``Router.drain`` empties the replica
    IMMEDIATELY: waiting requests requeue, residents live-migrate
    mid-flight (no waiting them out), the drain event carries the
    structured migrated/residents_in_place split, migrate events carry
    the byte/latency accounting, and the run stays token-identical."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (int(rng.randint(5, 13)),))
              .astype(np.int32), int(rng.randint(3, 9)), {})
             for _ in range(8)]
    base = _baseline(model, params, trace)

    out = tmp_path / "drain"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        router = Router(model, params, replicas=2,
                        placement="round_robin", **_KW)
        reqs = [router.submit(p, m) for p, m, _ in trace]
        router.warmup()
        for _ in range(3):
            router.step()
        src = router.engines[0]
        had_residents = any(s.request is not None
                            for s in src.sched.slots)
        router.drain(0)
        # the drain completed NOW: nothing resident, nothing queued
        assert had_residents and router.migrations > 0
        assert all(s.request is None for s in src.sched.slots)
        assert not src.sched.waiting
        assert src.blocks.num_used == 0
        router.run()
        obs.flush()
    finally:
        obs.reset()
    assert [list(router.output_ids(q)) for q in reqs] == base
    assert len(router.finished) == len(trace)
    for eng in router.engines:
        assert eng.blocks.num_used == 0 and _conserved(eng)
    events = [e for _, e, err in obs.iter_events(
        str(out / "events.jsonl")) if err is None]
    drains = [e for e in events if e.get("event") == "drain"]
    assert len(drains) == 1
    assert drains[0]["migrated"] >= 1
    assert drains[0]["residents_in_place"] == 0
    migrates = [e for e in events if e.get("event") == "migrate"]
    assert len(migrates) == router.migrations
    for e in migrates:
        assert e["from_replica"] == 0 and e["to_replica"] == 1
        assert isinstance(e["migration_bytes"], int)
        assert isinstance(e["restore_s"], float)
    assert any(e["migration_bytes"] > 0 for e in migrates)


def test_disaggregated_roles_token_identical_and_separated(gpt2_setup):
    """The prefill/decode split end to end: token identity vs one
    engine, ZERO decode iterations on the prefill replica, zero
    submissions on the decode replica, every request handed over the
    transport exactly once, and the fleet summary's per-role
    attribution present."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(4)
    trace = [(rng.randint(1, 120, (int(rng.randint(5, 13)),))
              .astype(np.int32), int(rng.randint(3, 9)), {})
             for _ in range(6)]
    base = _baseline(model, params, trace)
    router = Router(model, params, roles="prefill:1,decode:1", **_KW)
    reqs = [router.submit(p, m) for p, m, _ in trace]
    router.run()
    assert [list(router.output_ids(q)) for q in reqs] == base
    assert router.role_of == ["prefill", "decode"]
    pre, dec = router.engines
    assert pre.stats().decode_steps == 0
    assert dec.stats().prefill_dispatches == 0
    assert router.migrations == len(trace)
    assert pre.stats().migrations_out == len(trace)
    assert dec.stats().migrations_in == len(trace)
    assert all(router.replica_of(q) == 1 for q in reqs)
    slo = router.slo_summary()
    assert slo["roles"] == "prefill:1,decode:1"
    assert set(slo["per_role"]) == {"prefill", "decode"}
    assert slo["per_role"]["prefill"]["decode_steps"] == 0
    assert slo["migrations"] == len(trace)
    assert slo["migration_bytes"] > 0
    # an impossible request is refused at SUBMIT, not stuck mid-fleet
    with pytest.raises(ValueError):
        router.submit(rng.randint(1, 120, (60,)).astype(np.int32), 16)


def test_length_aware_heterogeneous_fleet(gpt2_setup):
    """Heterogeneous fleets: per-replica overrides build a small and a
    large replica (same pool signature — transport-compatible), and
    length-aware placement sends long prompts to the deep class, short
    ones to the shallow class, token-identically."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(6)
    short = [(rng.randint(1, 120, (5,)).astype(np.int32), 4, {})
             for _ in range(2)]
    long_ = [(rng.randint(1, 120, (16,)).astype(np.int32), 4, {})
             for _ in range(2)]
    trace = [row for pair in zip(short, long_) for row in pair]
    base = _baseline(model, params, trace)
    router = Router(model, params, replicas=2, placement="length_aware",
                    replica_kwargs=[{"num_blocks": 20}, {}],
                    length_threshold=10, **_KW)
    assert (router.engines[0].blocks.num_blocks
            < router.engines[1].blocks.num_blocks)
    reqs = [router.submit(p, m) for p, m, _ in trace]
    owners = [router.replica_of(q) for q in reqs]
    assert owners == [0, 1, 0, 1]     # short -> shallow, long -> deep
    router.run()
    assert [list(router.output_ids(q)) for q in reqs] == base


def test_parse_roles_knob(monkeypatch):
    assert parse_roles(None) is None
    assert parse_roles("") is None
    assert parse_roles("prefill:1,decode:2") == {"prefill": 1,
                                                 "decode": 2}
    assert parse_roles({"prefill": 2, "decode": 1}) == {"prefill": 2,
                                                        "decode": 1}
    monkeypatch.setenv("HSTD_SERVE_ROLES", "prefill:1,decode:1")
    assert parse_roles(None) == {"prefill": 1, "decode": 1}
    for bad in ("prefill:1", "decode:2", "prefill:0,decode:1",
                "verify:1,decode:1", "prefill=1,decode=1",
                "prefill:x,decode:1"):
        with pytest.raises(ValueError):
            parse_roles(bad)


def test_roles_contradicting_replicas_refused(gpt2_setup):
    _cfg, model, params = gpt2_setup
    with pytest.raises(ValueError):
        Router(model, params, replicas=3, roles="prefill:1,decode:1",
               **_KW)
    # matching counts are fine
    r = Router(model, params, replicas=2, roles="prefill:1,decode:1",
               **_KW)
    assert r.n == 2 and r.roles == {"prefill": 1, "decode": 1}
