"""Streaming data tier: ArrayDataset-equivalence, batch-composition
independence of MLM masking, bounded resident memory, and the CLI path.

The reference materializes its whole dataset densely in host memory
(reference ``scripts/train.py:80-83``); this tier replaces that with a
line-offset index + per-batch tokenization (SURVEY.md §2 quirk fix)."""

import json

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    LineCorpus,
    ShardedBatcher,
    StreamingTextDataset,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)

SEQ = 32


def _write_jsonl(path, texts, labels=None):
    with open(path, "w") as f:
        for i, t in enumerate(texts):
            rec = {"text": t}
            if labels is not None:
                rec["label"] = int(labels[i])
            f.write(json.dumps(rec) + "\n")
    return str(path)


@pytest.fixture()
def corpus_file(tmp_path):
    texts, labels = synthetic_text_classification(64, seed=0)
    return _write_jsonl(tmp_path / "train.jsonl", texts, labels), texts, labels


def test_line_corpus_random_access(corpus_file):
    path, texts, labels = corpus_file
    corpus = LineCorpus(path)
    assert len(corpus) == len(texts)
    idx = np.array([5, 0, 63, 5])
    got, lab = corpus.read_rows(idx)
    assert got == [texts[5], texts[0], texts[63], texts[5]]
    assert lab == [labels[5], labels[0], labels[63], labels[5]]


def test_line_corpus_txt_and_trailing_newline(tmp_path):
    p = tmp_path / "c.txt"
    p.write_text("alpha beta\ngamma\ndelta epsilon\n")
    corpus = LineCorpus(str(p))
    assert len(corpus) == 3
    got, lab = corpus.read_rows(np.array([2, 0]))
    assert got == ["delta epsilon", "alpha beta"] and lab is None


def test_streaming_causal_lm_matches_materialized(corpus_file):
    """causal-lm has no randomness: streaming and materialized must
    produce bit-identical batches from the same ShardedBatcher seed —
    hence identical loss curves at equal data, the equivalence the
    VERDICT asks for, checked at the strictest level."""
    path, texts, _ = corpus_file
    tok = WordHashTokenizer(vocab_size=512)
    mesh = build_mesh(MeshConfig())
    mat = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="causal-lm",
                                  max_length=SEQ)
    assert len(stream) == len(mat)
    for epoch in (0, 1):
        b_mat = list(ShardedBatcher(mat, 16, mesh, shuffle=True,
                                    seed=7).local_batches(epoch))
        b_str = list(ShardedBatcher(stream, 16, mesh, shuffle=True,
                                    seed=7).local_batches(epoch))
        assert len(b_mat) == len(b_str)
        for a, b in zip(b_mat, b_str):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_streaming_seq_cls_matches_materialized(corpus_file):
    path, texts, labels = corpus_file
    tok = WordHashTokenizer(vocab_size=512)
    mat = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="seq-cls",
                                  max_length=SEQ)
    idx = np.arange(16)
    a, b = mat[idx], stream[idx]
    for k in ("input_ids", "attention_mask", "labels"):
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_streaming_mlm_batch_composition_independent(corpus_file):
    """A row's masks depend only on (seed, epoch, row) — gathering it in
    different batches, alone, or in different order must not change
    them. This is what makes the shared epoch permutation sufficient for
    multi-host agreement without communication."""
    path, _, _ = corpus_file
    tok = WordHashTokenizer(vocab_size=512)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="mlm",
                                  max_length=SEQ, seed=11)
    a = stream[np.arange(0, 8)]
    b = stream[np.array([3])]
    np.testing.assert_array_equal(a["input_ids"][3], b["input_ids"][0])
    np.testing.assert_array_equal(a["labels"][3], b["labels"][0])
    c = stream[np.array([7, 3, 0])]
    np.testing.assert_array_equal(c["input_ids"][1], b["input_ids"][0])
    # epoch changes the draw; determinism within an epoch
    stream.begin_epoch(1)
    d = stream[np.array([3])]
    assert (d["labels"] != b["labels"]).any()
    stream.begin_epoch(0)
    e = stream[np.array([3])]
    np.testing.assert_array_equal(e["labels"], b["labels"])


def test_streaming_mlm_statistics(corpus_file):
    path, _, _ = corpus_file
    tok = WordHashTokenizer(vocab_size=512)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="mlm",
                                  max_length=SEQ, seed=0)
    batch = stream[np.arange(64)]
    masked = batch["labels"] != -100
    frac = masked.sum() / (batch["attention_mask"].sum() - 2 * 64)
    assert 0.06 < frac < 0.3
    mask_frac = (batch["input_ids"][masked] == tok.mask_token_id).mean()
    assert 0.6 < mask_frac < 0.95


def test_streaming_resident_memory_is_offsets_only(tmp_path):
    """The streaming dataset pins ~8 bytes/row regardless of text size;
    the materialized equivalent pins the full padded [N, L] columns.
    At 512 tokens that's a ~250x gap — the corpus-larger-than-RAM
    property at test scale."""
    texts, labels = synthetic_text_classification(256, seed=1)
    path = _write_jsonl(tmp_path / "t.jsonl", texts, labels)
    tok = WordHashTokenizer(vocab_size=512)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="mlm",
                                  max_length=512)
    mat = ArrayDataset.from_mlm_texts(tok, texts, max_length=512)
    mat_bytes = sum(v.nbytes for v in mat.columns.values())
    assert stream.resident_bytes() < mat_bytes / 100
    assert stream.resident_bytes() == (256 + 1) * 8


def test_streaming_rejects_buckets_and_bad_tasks(corpus_file):
    path, _, _ = corpus_file
    tok = WordHashTokenizer(vocab_size=512)
    stream = StreamingTextDataset(LineCorpus(path), tok, task="mlm",
                                  max_length=SEQ)
    mesh = build_mesh(MeshConfig())
    with pytest.raises(ValueError, match="bucket"):
        ShardedBatcher(stream, 16, mesh, bucket_sizes=[16, 32])
    with pytest.raises(ValueError, match="streaming tier supports"):
        StreamingTextDataset(LineCorpus(path), tok, task="qa")


def test_streaming_seq2seq_rejects_txt_corpus(tmp_path):
    """A .txt corpus has no source/target fields: fail at construction,
    not minutes later at the first batch."""
    p = tmp_path / "c.txt"
    p.write_text("one\ntwo\n")
    tok = WordHashTokenizer(vocab_size=512)
    with pytest.raises(ValueError, match="jsonl"):
        StreamingTextDataset(LineCorpus(str(p)), tok, task="seq2seq")


def test_streaming_cli_mlm(tmp_path, devices8):
    """scripts/train.py --streaming true trains MLM end to end from a
    disk corpus and writes the same results contract."""
    import transformers

    from scripts.train import main as train_main

    texts, labels = synthetic_text_classification(128, seed=0)
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    _write_jsonl(data_dir / "train.jsonl", texts, labels)
    _write_jsonl(data_dir / "test.jsonl", texts[:32], labels[:32])
    mdir = str(tmp_path / "cfg")
    transformers.BertConfig(
        vocab_size=4096, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=SEQ).save_pretrained(mdir)
    out = str(tmp_path / "out")
    train_main([
        "--task", "mlm", "--dataset_path", str(data_dir),
        "--streaming", "true", "--from_scratch", "true",
        "--model_name_or_path", mdir, "--epochs", "1",
        "--train_batch_size", "2", "--dtype", "float32",
        "--max_seq_length", str(SEQ), "--learning_rate", "1e-3",
        "--scale_lr_by_world_size", "false",
        "--output_data_dir", out, "--model_dir", str(tmp_path / "model"),
    ])
    text = (tmp_path / "out" / "train_results.txt").read_text()
    assert "train_runtime" in text and "loss" in text


def test_streaming_seq2seq_matches_materialized(tmp_path):
    """seq2seq streaming encodes each batch through the SAME from_seq2seq
    builder — bit-identical columns to the materialized dataset."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization,
    )

    sources, targets = synthetic_summarization(32, seed=2)
    path = tmp_path / "train.jsonl"
    with open(path, "w") as f:
        for s, t in zip(sources, targets):
            f.write(json.dumps({"source": s, "target": t}) + "\n")
    tok = WordHashTokenizer(vocab_size=512)
    kw = dict(max_target_length=12, decoder_start_token_id=0,
              pad_token_id=0, eos_token_id=1)
    mat = ArrayDataset.from_seq2seq(tok, sources, targets,
                                    max_source_length=SEQ, **kw)
    stream = StreamingTextDataset(LineCorpus(str(path)), tok,
                                  task="seq2seq", max_length=SEQ,
                                  seq2seq_kwargs=kw)
    assert len(stream) == len(mat)
    idx = np.array([5, 0, 31, 17])
    a, b = mat[idx], stream[idx]
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_native_line_boundaries_matches_python(tmp_path):
    """The C++ pread+memchr indexer and the Python line loop build the
    IDENTICAL boundary array — with and without a trailing newline, and
    with CRLF rows (skips when no toolchain: the fallback IS the loop)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.native import (
        native_line_boundaries,
    )

    cases = {
        "lf.jsonl": b'{"text": "a"}\n{"text": "bb"}\n{"text": "ccc"}\n',
        "no_trail.txt": b"alpha\nbeta\ngamma",
        "crlf.txt": b"one\r\ntwo\r\nthree\r\n",
        "empty.txt": b"",
    }
    for name, payload in cases.items():
        p = tmp_path / name
        p.write_bytes(payload)
        native = native_line_boundaries(str(p))
        if native is None:
            pytest.skip("no native toolchain")
        offsets = [0]
        with open(p, "rb") as f:
            for line in f:
                offsets.append(offsets[-1] + len(line))
        np.testing.assert_array_equal(native, np.asarray(offsets, np.int64),
                                      err_msg=name)
