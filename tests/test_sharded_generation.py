"""Generation under a tensor-parallel mesh (serving sharded models).

A model too big for one chip serves with its params sharded over the
``tensor`` axis: the generate functions are mesh-agnostic (the ambient
mesh + param shardings drive XLA's collective insertion), so greedy,
beam, and speculative decode must produce token-identical output to
the single-device run — the certifying evidence for sharded serving.
"""

import numpy as np
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    beam_search_causal,
    generate_causal,
    generate_speculative,
    self_draft,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    param_shardings,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
    use_mesh,
)


def _model():
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    return model, init_params(model, cfg, seed=0)


def test_generation_under_tp_mesh_matches_single_device(devices8):
    model, params = _model()
    ids = np.random.RandomState(0).randint(3, 128, (2, 7))
    greedy_ref = np.asarray(generate_causal(model, params, ids,
                                            max_new_tokens=10))
    beam_ref = np.asarray(beam_search_causal(model, params, ids,
                                             num_beams=3,
                                             max_new_tokens=8))
    draft, d_params = self_draft(model, params, 1)
    spec_ref = np.asarray(generate_speculative(model, params, draft,
                                               d_params, ids,
                                               max_new_tokens=10))

    mesh = build_mesh(MeshConfig(dp=1, tp=2), devices=devices8[:2])
    sharded = jax.device_put(params, param_shardings(params, mesh))
    d_sharded = jax.device_put(d_params, param_shardings(d_params, mesh))
    with use_mesh(mesh):
        greedy = np.asarray(generate_causal(model, sharded, ids,
                                            max_new_tokens=10))
        beam = np.asarray(beam_search_causal(model, sharded, ids,
                                             num_beams=3,
                                             max_new_tokens=8))
        spec = np.asarray(generate_speculative(model, sharded, draft,
                                               d_sharded, ids,
                                               max_new_tokens=10))
    np.testing.assert_array_equal(greedy, greedy_ref)
    np.testing.assert_array_equal(beam, beam_ref)
    np.testing.assert_array_equal(spec, spec_ref)
