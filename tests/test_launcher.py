"""Launcher: hyperparam serialization, slice topology, job naming, the
gcloud command builder, and a REAL 2-process local-slice-simulator run
with JAX coordinator rendezvous (SURVEY.md §4 multi-host rig)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.launch import (
    SliceConfig,
    TPUJob,
    TPUVMBackend,
    make_job_name,
    to_argv,
)


def test_to_argv_serialization():
    argv = to_argv({"epochs": 3, "learning_rate": 5e-5, "do_train": True,
                    "model_name_or_path": "bert-base"})
    assert argv == ["--epochs", "3", "--learning_rate", "5e-05",
                    "--do_train", "true", "--model_name_or_path", "bert-base"]


def test_slice_topology():
    s = SliceConfig.parse("v5e-32")
    assert (s.num_hosts, s.chips_per_host) == (8, 4)
    assert SliceConfig.parse("v4-8").num_hosts == 2
    assert SliceConfig.parse("v5e-4").num_hosts == 1
    assert SliceConfig.parse("cpu-8").accelerator == "cpu"
    with pytest.raises(ValueError):
        SliceConfig.parse("h100-8")


def test_job_name():
    name = make_job_name("bert/large_wwm", when=1700000000.0)
    assert name.startswith("bert-large-wwm-20")
    assert "/" not in name and "_" not in name


def test_tpu_vm_command_built_not_run(tmp_path):
    backend = TPUVMBackend(tpu_name="my-slice", zone="us-east5-b")
    job = TPUJob(slice_spec="v5e-32", hyperparameters={"epochs": 1},
                 job_root=str(tmp_path))
    handle = backend.launch(job, "jobname", str(tmp_path / "jobname"))
    cmd = handle.remote_command
    assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "my-slice"]
    assert "--worker=all" in cmd
    assert any("--epochs 1" in c for c in cmd)
    assert handle.procs == []  # constructed, not executed


def test_tpu_vm_backend_executes_through_stub_gcloud(tmp_path, monkeypatch):
    """TPUVMBackend(execute=True) end to end against a stub ``gcloud``
    on PATH that runs the ``--command=`` payload locally — the launch /
    log-capture / wait flow actually executes (zero-egress stand-in for
    a real slice; the command CONTENT is covered by
    test_tpu_vm_command_built_not_run)."""
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    gcloud = stub_dir / "gcloud"
    gcloud.write_text(textwrap.dedent("""\
        #!/bin/bash
        # stub: find the --command= arg and run it in a local shell,
        # like the real gcloud would on every worker
        for a in "$@"; do
          case "$a" in --command=*) exec bash -c "${a#--command=}";; esac
        done
        echo "no --command passed" >&2; exit 9
    """))
    gcloud.chmod(0o755)
    monkeypatch.setenv("PATH", f"{stub_dir}:{os.environ['PATH']}")

    src = tmp_path / "src"
    src.mkdir()
    (src / "entry.py").write_text(textwrap.dedent("""
        import json, os, sys
        out = os.environ["TPU_OUTPUT_DATA_DIR"]
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, "ran.json"), "w") as f:
            json.dump({"argv": sys.argv[1:],
                       "model_dir": os.environ["TPU_MODEL_DIR"]}, f)
    """))

    job = TPUJob(entry_point="entry.py", source_dir=str(src),
                 slice_spec="v5e-8", hyperparameters={"epochs": 2},
                 job_root=str(tmp_path / "jobs"))
    backend = TPUVMBackend(tpu_name="stub-slice", zone="us-x1-a",
                           execute=True)
    job_dir = str(tmp_path / "jobs" / "j1")
    os.makedirs(job_dir, exist_ok=True)
    handle = backend.launch(job, "j1", job_dir)
    assert handle.procs, "execute=True must spawn the gcloud process"
    codes = handle.wait(timeout=60)
    assert codes == [0]
    with open(os.path.join(handle.output_data_dir, "ran.json")) as f:
        ran = json.load(f)
    assert ran["argv"] == ["--epochs", "2"]
    assert ran["model_dir"] == handle.model_dir
    assert os.path.exists(os.path.join(job_dir, "gcloud.log"))


def test_failed_rank_terminates_survivors(tmp_path):
    """One rank dies, the other hangs (as at a collective): wait() must
    kill the survivor after the grace period and raise — not deadlock."""
    import time as _time
    entry = tmp_path / "crashy.py"
    entry.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ["TPU_PROCESS_ID"] == "1":
            sys.exit(3)
        time.sleep(120)  # simulates a rank stuck waiting for the dead one
    """))
    job = TPUJob(entry_point=str(entry), source_dir=str(tmp_path),
                 slice_spec="cpu-2", num_hosts=2,
                 job_root=str(tmp_path / "jobs"))
    t0 = _time.time()
    handle = job.fit(wait=False)
    with pytest.raises(RuntimeError, match="failed with codes"):
        handle.wait(grace_period=2.0)
    assert _time.time() - t0 < 60  # well under the sleep(120) hang


@pytest.mark.slow
def test_local_two_host_training_job(tmp_path):
    """launch.py-equivalent zero→aha: 2 simulated hosts run the REAL
    training entry point — rendezvous, sharded data, allreduce, eval,
    cross-host gather + HF export (the reference's estimator.fit() path,
    launch.py:55, without a cloud)."""
    import transformers
    cfg_dir = str(tmp_path / "cfg")
    transformers.BertConfig(
        vocab_size=256, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64).save_pretrained(cfg_dir)
    job = TPUJob(entry_point="scripts/train.py", source_dir=os.getcwd(),
                 slice_spec="cpu-8", num_hosts=2,
                 hyperparameters={
                     "model_name_or_path": cfg_dir, "from_scratch": True,
                     "dataset": "synthetic", "epochs": 1,
                     "train_batch_size": 2, "dtype": "float32",
                     "max_seq_length": 32, "max_train_samples": 32,
                     "max_eval_samples": 16, "learning_rate": 1e-3,
                     "scale_lr_by_world_size": False,
                 },
                 job_root=str(tmp_path / "jobs"), coordinator_port=8498,
                 env={"PYTHONPATH": os.getcwd()})
    handle = job.fit(wait=True)
    assert handle.returncodes == [0, 0]
    assert os.path.exists(os.path.join(handle.model_dir, "model.safetensors"))
    assert os.path.exists(os.path.join(handle.output_data_dir,
                                       "eval_results.txt"))


@pytest.mark.slow
def test_local_two_host_job_end_to_end(tmp_path):
    """Two simulated hosts rendezvous via the JAX coordinator, shard the
    batch, allreduce gradients, and host 0 writes the artifacts — the
    full multi-host code path with no TPU and no cluster."""
    entry = tmp_path / "entry.py"
    entry.write_text(textwrap.dedent("""
        import json, os, sys
        import jax
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
            MeshConfig, build_mesh, initialize_distributed)
        pid, pcount = initialize_distributed()
        assert pcount == 2, pcount
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = build_mesh(MeshConfig(dp=-1))
        # one global array sharded over both hosts' devices; a global sum
        # exercises the cross-process collective path
        import numpy as np
        local = np.full((4, 2), 1 + pid, np.float32)
        global_arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(("data", "fsdp"))), local)
        total = jax.jit(lambda x: jnp.sum(x))(global_arr)
        out_dir = os.environ["TPU_OUTPUT_DATA_DIR"]
        if jax.process_index() == 0:
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, "result.json"), "w") as f:
                json.dump({"total": float(total), "pcount": pcount}, f)
    """))
    job = TPUJob(entry_point=str(entry), source_dir=os.getcwd(),
                 slice_spec="cpu-8", num_hosts=2,
                 hyperparameters={}, job_root=str(tmp_path / "jobs"),
                 coordinator_port=8497,
                 env={"PYTHONPATH": os.getcwd()})
    handle = job.fit(wait=True)
    assert handle.returncodes == [0, 0]
    with open(os.path.join(handle.output_data_dir, "result.json")) as f:
        result = json.load(f)
    # 8 rows × 2 cols: hosts contribute 4×2 of 1s and 4×2 of 2s
    assert result == {"total": 24.0, "pcount": 2}
    assert os.path.exists(os.path.join(handle.job_dir, "host_0.log"))
    assert os.path.exists(os.path.join(handle.job_dir, "host_1.log"))


@pytest.mark.slow
def test_kill_relaunch_resume_drill(tmp_path):
    """The SURVEY §5.3 preemption story, composed end to end: a 2-host
    job loses rank 1 to SIGKILL mid-epoch (after an async mid-epoch
    checkpoint committed), the launcher kills the hung survivor and
    raises; relaunching the SAME job dirs resumes from the committed
    mid-epoch position (step-in-epoch > 0) and runs to completion,
    writing the terminal results files exactly once."""
    import re
    import transformers

    cfg_dir = str(tmp_path / "cfg")
    transformers.BertConfig(
        vocab_size=256, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64).save_pretrained(cfg_dir)
    ckpt_dir = str(tmp_path / "ckpt")
    out_dir = str(tmp_path / "out")
    model_dir = str(tmp_path / "model")

    # entry wrapper: rank 1 self-SIGKILLs the moment the first COMMITTED
    # mid-epoch checkpoint appears (orbax renames the tmp dir to a bare
    # step name only at commit, so a digit-named dir == durable)
    entry = tmp_path / "drill_entry.py"
    entry.write_text(textwrap.dedent("""
        import os, signal, sys, threading, time

        if os.environ.get("DRILL_KILL") == "1" \\
                and os.environ["TPU_PROCESS_ID"] == "1":
            ckpt = os.environ["DRILL_CKPT_DIR"]

            def watchdog():
                while True:
                    try:
                        if any(d.isdigit() and int(d) > 0
                               for d in os.listdir(ckpt)):
                            os.kill(os.getpid(), signal.SIGKILL)
                    except FileNotFoundError:
                        pass
                    time.sleep(0.1)

            threading.Thread(target=watchdog, daemon=True).start()
        from scripts.train import main
        main(sys.argv[1:])
    """))

    hyper = {
        "model_name_or_path": cfg_dir, "from_scratch": True,
        "dataset": "synthetic", "epochs": 2,
        "train_batch_size": 2, "dtype": "float32",
        "max_seq_length": 32, "max_train_samples": 128,
        "max_eval_samples": 16, "learning_rate": 1e-3,
        "scale_lr_by_world_size": False,
        "checkpoint_dir": ckpt_dir, "checkpoint_every_steps": 4,
        "output_data_dir": out_dir, "model_dir": model_dir,
    }
    common = dict(entry_point=str(entry), source_dir=os.getcwd(),
                  slice_spec="cpu-2", num_hosts=2,
                  hyperparameters=hyper, job_root=str(tmp_path / "jobs"))

    job1 = TPUJob(coordinator_port=8495,
                  env={"PYTHONPATH": os.getcwd(), "DRILL_KILL": "1",
                       "DRILL_CKPT_DIR": ckpt_dir}, **common)
    handle1 = job1.fit(wait=False)
    with pytest.raises(RuntimeError, match="failed with codes"):
        handle1.wait(grace_period=5.0)
    # the crash left a committed checkpoint and NO terminal results
    committed = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
    assert committed, "no committed checkpoint survived the kill"
    assert not os.path.exists(os.path.join(out_dir, "train_results.txt"))

    job2 = TPUJob(coordinator_port=8494,
                  env={"PYTHONPATH": os.getcwd()}, **common)
    handle2 = job2.fit(wait=True)
    assert handle2.returncodes == [0, 0]
    log0 = open(os.path.join(handle2.job_dir, "host_0.log")).read()
    m = re.search(r"resuming from epoch (\d+) \(step-in-epoch (\d+)\)", log0)
    assert m, "relaunch did not restore the checkpoint"
    assert int(m.group(2)) > 0, "resume was not mid-epoch"
    # terminal contract written exactly once, by the relaunch
    results = open(os.path.join(out_dir, "train_results.txt")).read()
    assert results.count("train_runtime") == 1
    assert os.path.exists(os.path.join(out_dir, "eval_results.txt"))
    assert os.path.exists(os.path.join(model_dir, "model.safetensors"))


@pytest.mark.slow
def test_local_two_host_dcn_axis_job(tmp_path):
    """Two simulated hosts with ONE device each train over a dcn2 mesh:
    the ``dcn`` axis boundary IS the process boundary (each host models
    one slice), so the outer leg of the hierarchical gradient all-reduce
    genuinely crosses processes — the dp-over-dcn × dp-over-ici
    groundwork (SURVEY L-1/§5.8: ICI *and* DCN)."""
    entry = tmp_path / "entry.py"
    entry.write_text(textwrap.dedent("""
        import json, os
        import jax
        import numpy as np
        from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
        from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
            ArrayDataset, ShardedBatcher, WordHashTokenizer)
        from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
            synthetic_text_classification)
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
            BertForSequenceClassification)
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
            EncoderConfig)
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
            AXIS_DCN, MeshConfig, build_mesh, initialize_distributed)
        from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

        pid, pcount = initialize_distributed()
        assert pcount == 2, pcount
        mesh = build_mesh(MeshConfig(dp=-1, dcn_dp=2))
        assert mesh.shape[AXIS_DCN] == 2
        # every run along the dcn axis must cross the process boundary:
        # position 0 and 1 on the axis live in different processes
        axes = list(mesh.axis_names)
        devs = np.moveaxis(mesh.devices, axes.index(AXIS_DCN), 0)
        procs = np.vectorize(lambda d: d.process_index)(devs)
        assert (procs[0] != procs[1]).all(), procs
        seq = 16
        model_cfg = EncoderConfig(
            vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_position_embeddings=seq)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          rng_impl="threefry", epochs=1, dcn_dp=2)
        trainer = Trainer(cfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, labels = synthetic_text_classification(32, seed=0)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False, seed=0)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        out_dir = os.environ["TPU_OUTPUT_DATA_DIR"]
        if jax.process_index() == 0:
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, "result.json"), "w") as f:
                json.dump({"losses": losses}, f)
    """))
    job = TPUJob(entry_point=str(entry), source_dir=os.getcwd(),
                 slice_spec="cpu-2", num_hosts=2,
                 hyperparameters={}, job_root=str(tmp_path / "jobs"),
                 coordinator_port=8493,
                 env={"PYTHONPATH": os.getcwd()})
    handle = job.fit(wait=True)
    assert handle.returncodes == [0, 0]
    with open(os.path.join(handle.output_data_dir, "result.json")) as f:
        result = json.load(f)
    assert len(result["losses"]) == 2
    assert all(np.isfinite(l) for l in result["losses"])


@pytest.mark.slow
def test_local_two_host_moe_expert_parallel_job(tmp_path):
    """Two simulated hosts with ONE device each train a MoE model with
    ep=2 — the expert axis IS the process boundary, so the token
    all-to-alls and the expert-sharded optimizer state genuinely cross
    hosts via the real JAX coordinator (an 8-device-per-host layout
    would keep expert pairs intra-host and prove nothing)."""
    entry = tmp_path / "entry.py"
    entry.write_text(textwrap.dedent("""
        import json, os
        import jax
        import numpy as np
        from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
        from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
            ArrayDataset, ShardedBatcher, WordHashTokenizer)
        from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
            synthetic_text_classification)
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
            BertForSequenceClassification)
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
            EncoderConfig)
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
            MeshConfig, build_mesh, initialize_distributed)
        from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

        pid, pcount = initialize_distributed()
        assert pcount == 2, pcount
        mesh = build_mesh(MeshConfig(dp=-1, ep=2))
        assert mesh.shape["expert"] == 2
        # one device per host: every expert pair spans both processes
        procs = {d.process_index for d in mesh.devices.ravel()}
        assert len(jax.local_devices()) == 1 and procs == {0, 1}
        seq = 16
        model_cfg = EncoderConfig(
            vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_position_embeddings=seq,
            num_experts=4, expert_top_k=2)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          rng_impl="threefry", epochs=1, num_experts=4, ep=2)
        trainer = Trainer(cfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, labels = synthetic_text_classification(32, seed=0)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False, seed=0)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        out_dir = os.environ["TPU_OUTPUT_DATA_DIR"]
        if jax.process_index() == 0:
            os.makedirs(out_dir, exist_ok=True)
            with open(os.path.join(out_dir, "result.json"), "w") as f:
                json.dump({"losses": losses}, f)
    """))
    job = TPUJob(entry_point=str(entry), source_dir=os.getcwd(),
                 slice_spec="cpu-2", num_hosts=2,
                 hyperparameters={}, job_root=str(tmp_path / "jobs"),
                 coordinator_port=8496,
                 env={"PYTHONPATH": os.getcwd()})
    handle = job.fit(wait=True)
    assert handle.returncodes == [0, 0]
    with open(os.path.join(handle.output_data_dir, "result.json")) as f:
        result = json.load(f)
    assert len(result["losses"]) == 2
    assert all(np.isfinite(l) for l in result["losses"])


@pytest.mark.slow
def test_local_two_host_llama_causal_lm_job(tmp_path):
    """The modern-decoder family through the full multi-process path:
    2 simulated hosts fine-tune a tiny Llama (GQA) causal-lm with the
    fused vocab-CE loss — rendezvous, sharded data, allreduce, export."""
    import transformers
    cfg_dir = str(tmp_path / "cfg")
    transformers.LlamaConfig(
        vocab_size=256, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        tie_word_embeddings=False).save_pretrained(cfg_dir)
    job = TPUJob(entry_point="scripts/train.py", source_dir=os.getcwd(),
                 slice_spec="cpu-8", num_hosts=2,
                 hyperparameters={
                     "model_name_or_path": cfg_dir, "from_scratch": True,
                     "task": "causal-lm", "dataset": "synthetic",
                     "epochs": 1, "train_batch_size": 2,
                     "dtype": "float32", "max_seq_length": 32,
                     "max_train_samples": 32, "max_eval_samples": 16,
                     "learning_rate": 1e-3,
                     "scale_lr_by_world_size": False,
                 },
                 job_root=str(tmp_path / "jobs"), coordinator_port=8499,
                 env={"PYTHONPATH": os.getcwd()})
    handle = job.fit(wait=True)
    assert handle.returncodes == [0, 0]
    assert os.path.exists(os.path.join(handle.model_dir,
                                       "model.safetensors"))
    import json as _json
    with open(os.path.join(handle.model_dir, "config.json")) as f:
        assert _json.load(f)["model_type"] == "llama"


def _stub_gcloud_multiworker(stub_dir, n_workers=2):
    """A stub ``gcloud`` that fans the --command= payload out to
    ``n_workers`` local shells (TPU_WORKER_ID set like the real tpu-vm
    ssh does per host) and exits with the first nonzero worker rc —
    matching real gcloud's any-worker-fails behavior."""
    gcloud = stub_dir / "gcloud"
    gcloud.write_text(textwrap.dedent(f"""\
        #!/bin/bash
        for a in "$@"; do
          case "$a" in --command=*) CMD="${{a#--command=}}";; esac
        done
        [ -z "$CMD" ] && {{ echo "no --command passed" >&2; exit 9; }}
        rc=0
        for w in $(seq 0 {n_workers - 1}); do
          TPU_WORKER_ID=$w bash -c "$CMD" || {{ r=$?; [ $rc -eq 0 ] && rc=$r; }}
        done
        exit $rc
    """))
    gcloud.chmod(0o755)


def test_tpu_vm_worker_subset_failure_raises_and_keeps_artifacts(
        tmp_path, monkeypatch):
    """One of two workers dies mid-job (nonzero ssh rc on a worker
    subset): wait() must raise with the failure code, and the surviving
    worker's artifacts plus the gcloud log must still be collected."""
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    _stub_gcloud_multiworker(stub_dir)
    monkeypatch.setenv("PATH", f"{stub_dir}:{os.environ['PATH']}")

    src = tmp_path / "src"
    src.mkdir()
    (src / "entry.py").write_text(textwrap.dedent("""
        import json, os, sys
        w = os.environ["TPU_WORKER_ID"]
        out = os.environ["TPU_OUTPUT_DATA_DIR"]
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, f"ran_w{w}.json"), "w") as f:
            json.dump({"worker": w}, f)
        if w == "1":
            print("worker 1 dying mid-job", file=sys.stderr)
            sys.exit(7)
    """))

    job = TPUJob(entry_point="entry.py", source_dir=str(src),
                 slice_spec="v5e-16", hyperparameters={},
                 job_root=str(tmp_path / "jobs"))
    backend = TPUVMBackend(tpu_name="stub-slice", zone="us-x1-a",
                           execute=True)
    job_dir = str(tmp_path / "jobs" / "jfail")
    os.makedirs(job_dir, exist_ok=True)
    handle = backend.launch(job, "jfail", job_dir)
    with pytest.raises(RuntimeError, match="failed with codes"):
        handle.wait(timeout=60)
    assert handle.returncodes == [7]
    # partial artifact collection: BOTH workers' outputs exist (worker 1
    # wrote before dying), and the gcloud log captured its last words
    assert os.path.exists(os.path.join(handle.output_data_dir,
                                       "ran_w0.json"))
    assert os.path.exists(os.path.join(handle.output_data_dir,
                                       "ran_w1.json"))
    with open(os.path.join(job_dir, "gcloud.log")) as f:
        assert "worker 1 dying mid-job" in f.read()


def test_tpu_vm_all_workers_fail_first_rc_wins(tmp_path, monkeypatch):
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    _stub_gcloud_multiworker(stub_dir)
    monkeypatch.setenv("PATH", f"{stub_dir}:{os.environ['PATH']}")
    src = tmp_path / "src"
    src.mkdir()
    (src / "entry.py").write_text(
        "import os, sys; sys.exit(3 if os.environ['TPU_WORKER_ID'] == '0'"
        " else 5)\n")
    job = TPUJob(entry_point="entry.py", source_dir=str(src),
                 slice_spec="v5e-16", hyperparameters={},
                 job_root=str(tmp_path / "jobs"))
    backend = TPUVMBackend(tpu_name="stub-slice", zone="us-x1-a",
                           execute=True)
    job_dir = str(tmp_path / "jobs" / "jall")
    os.makedirs(job_dir, exist_ok=True)
    handle = backend.launch(job, "jall", job_dir)
    with pytest.raises(RuntimeError, match="failed with codes"):
        handle.wait(timeout=60)
    assert handle.returncodes == [3]


def test_tpu_vm_hung_worker_times_out_and_terminates(tmp_path, monkeypatch):
    """A worker that never returns (dead VM, wedged ssh): wait(timeout)
    must terminate the gcloud process and raise instead of blocking."""
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    _stub_gcloud_multiworker(stub_dir)
    monkeypatch.setenv("PATH", f"{stub_dir}:{os.environ['PATH']}")
    src = tmp_path / "src"
    src.mkdir()
    (src / "entry.py").write_text(
        "import os, time\n"
        "time.sleep(120 if os.environ['TPU_WORKER_ID'] == '1' else 0)\n")
    job = TPUJob(entry_point="entry.py", source_dir=str(src),
                 slice_spec="v5e-16", hyperparameters={},
                 job_root=str(tmp_path / "jobs"))
    backend = TPUVMBackend(tpu_name="stub-slice", zone="us-x1-a",
                           execute=True)
    job_dir = str(tmp_path / "jobs" / "jhang")
    os.makedirs(job_dir, exist_ok=True)
    import time as _time
    t0 = _time.time()
    handle = backend.launch(job, "jhang", job_dir)
    with pytest.raises(subprocess.TimeoutExpired):
        handle.wait(timeout=3)
    assert _time.time() - t0 < 60
    # the stub gcloud (and its hung child) must be dead after terminate
    handle.procs[0].wait(timeout=10)
    assert handle.procs[0].poll() is not None
