"""Input-pipeline tests: per-host sharding math (fixing the reference's
every-rank-sees-all-data bug, SURVEY.md §2) and eval tail padding."""

import gc
import threading

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
    ArrayDataset,
    ShardedBatcher,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import MeshConfig, build_mesh


def _dataset(n=64, seq=8):
    return ArrayDataset({
        "input_ids": np.arange(n * seq, dtype=np.int32).reshape(n, seq),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": np.arange(n, dtype=np.int32),
    })


def test_hosts_partition_each_global_batch(devices8):
    """Simulate 4 hosts: their local batches must tile the global batch
    disjointly and identically ordered — no K×-data duplication."""
    mesh = build_mesh(MeshConfig(), devices=devices8)
    ds = _dataset(64)
    global_bs = 16
    per_host_batches = []
    for p in range(4):
        b = ShardedBatcher(ds, global_bs, mesh, shuffle=True, seed=7,
                           process_index=p, process_count=4)
        per_host_batches.append(list(b.local_batches(epoch=0)))
    steps = len(per_host_batches[0])
    assert steps == 64 // global_bs
    seen = []
    for s in range(steps):
        rows = np.concatenate([per_host_batches[p][s]["labels"] for p in range(4)])
        assert len(rows) == global_bs
        seen.append(rows)
    all_rows = np.concatenate(seen)
    # union over the epoch is exactly the dataset, each example once
    assert sorted(all_rows.tolist()) == list(range(64))


def test_epoch_shuffle_changes_order_deterministically():
    mesh = build_mesh(MeshConfig())
    ds = _dataset(32)
    b = ShardedBatcher(ds, 8, mesh, shuffle=True, seed=3,
                       process_index=0, process_count=1)
    e0a = np.concatenate([x["labels"] for x in b.local_batches(0)])
    e0b = np.concatenate([x["labels"] for x in b.local_batches(0)])
    e1 = np.concatenate([x["labels"] for x in b.local_batches(1)])
    np.testing.assert_array_equal(e0a, e0b)
    assert not np.array_equal(e0a, e1)


def test_eval_tail_padded_with_valid_mask():
    mesh = build_mesh(MeshConfig())
    ds = _dataset(20)
    b = ShardedBatcher(ds, 8, mesh, shuffle=False, drop_remainder=False,
                       process_index=0, process_count=1)
    batches = list(b.local_batches(0))
    assert len(batches) == 3
    assert batches[-1]["valid"].sum() == 4       # 20 = 8+8+4
    assert batches[-1]["labels"].shape == (8,)   # static shape kept
    assert batches[0]["valid"].sum() == 8


def test_train_drops_remainder():
    mesh = build_mesh(MeshConfig())
    b = ShardedBatcher(_dataset(20), 8, mesh, shuffle=False, drop_remainder=True,
                       process_index=0, process_count=1)
    assert b.steps_per_epoch() == 2


def test_global_arrays_sharded_over_mesh(devices8):
    mesh = build_mesh(MeshConfig(), devices=devices8)
    b = ShardedBatcher(_dataset(32), 16, mesh, shuffle=False)
    batch = next(b.global_arrays(0))
    arr = batch["input_ids"]
    assert arr.shape == (16, 8)
    # batch dim split over the 8-way data axis
    assert len(arr.sharding.device_set) == 8
    db = arr.sharding.shard_shape(arr.shape)
    assert db == (2, 8)


def test_indivisible_global_batch_rejected():
    mesh = build_mesh(MeshConfig())
    with pytest.raises(ValueError):
        ShardedBatcher(_dataset(16), 6, mesh, process_index=0, process_count=4)


def test_prefetch_iterator_values_and_exceptions():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    assert list(PrefetchIterator(iter(range(7)), depth=2)) == list(range(7))

    def boom():
        yield 1
        raise RuntimeError("producer failed")

    it = PrefetchIterator(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer failed"):
        next(it)


def test_prefetch_iterator_close_stops_thread():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(10_000)), depth=2)
    assert next(it) == 0
    thread = it._thread
    it.close()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_prefetch_iterator_gc_reclaims_thread():
    """Dropping the iterator without close() must stop the producer (the
    thread target must not keep the wrapper alive)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(10_000)), depth=2)
    assert next(it) == 0
    thread = it._thread
    del it
    gc.collect()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_prefetch_iterator_exhaustion_is_sticky():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(3)), depth=2)
    assert list(it) == [0, 1, 2]
    assert next(it, "default") == "default"   # must not block
    it2 = PrefetchIterator(iter(range(3)), depth=2)
    it2.close()
    assert next(it2, None) is None
