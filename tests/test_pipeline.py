"""Input-pipeline tests: per-host sharding math (fixing the reference's
every-rank-sees-all-data bug, SURVEY.md §2) and eval tail padding."""

import gc
import threading

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
    ArrayDataset,
    ShardedBatcher,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import MeshConfig, build_mesh


def _dataset(n=64, seq=8):
    return ArrayDataset({
        "input_ids": np.arange(n * seq, dtype=np.int32).reshape(n, seq),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": np.arange(n, dtype=np.int32),
    })


def test_hosts_partition_each_global_batch(devices8):
    """Simulate 4 hosts: their local batches must tile the global batch
    disjointly and identically ordered — no K×-data duplication."""
    mesh = build_mesh(MeshConfig(), devices=devices8)
    ds = _dataset(64)
    global_bs = 16
    per_host_batches = []
    for p in range(4):
        b = ShardedBatcher(ds, global_bs, mesh, shuffle=True, seed=7,
                           process_index=p, process_count=4)
        per_host_batches.append(list(b.local_batches(epoch=0)))
    steps = len(per_host_batches[0])
    assert steps == 64 // global_bs
    seen = []
    for s in range(steps):
        rows = np.concatenate([per_host_batches[p][s]["labels"] for p in range(4)])
        assert len(rows) == global_bs
        seen.append(rows)
    all_rows = np.concatenate(seen)
    # union over the epoch is exactly the dataset, each example once
    assert sorted(all_rows.tolist()) == list(range(64))


def test_epoch_shuffle_changes_order_deterministically():
    mesh = build_mesh(MeshConfig())
    ds = _dataset(32)
    b = ShardedBatcher(ds, 8, mesh, shuffle=True, seed=3,
                       process_index=0, process_count=1)
    e0a = np.concatenate([x["labels"] for x in b.local_batches(0)])
    e0b = np.concatenate([x["labels"] for x in b.local_batches(0)])
    e1 = np.concatenate([x["labels"] for x in b.local_batches(1)])
    np.testing.assert_array_equal(e0a, e0b)
    assert not np.array_equal(e0a, e1)


def test_eval_tail_padded_with_valid_mask():
    mesh = build_mesh(MeshConfig())
    ds = _dataset(20)
    b = ShardedBatcher(ds, 8, mesh, shuffle=False, drop_remainder=False,
                       process_index=0, process_count=1)
    batches = list(b.local_batches(0))
    assert len(batches) == 3
    assert batches[-1]["valid"].sum() == 4       # 20 = 8+8+4
    assert batches[-1]["labels"].shape == (8,)   # static shape kept
    assert batches[0]["valid"].sum() == 8


def test_train_drops_remainder():
    mesh = build_mesh(MeshConfig())
    b = ShardedBatcher(_dataset(20), 8, mesh, shuffle=False, drop_remainder=True,
                       process_index=0, process_count=1)
    assert b.steps_per_epoch() == 2


def test_global_arrays_sharded_over_mesh(devices8):
    mesh = build_mesh(MeshConfig(), devices=devices8)
    b = ShardedBatcher(_dataset(32), 16, mesh, shuffle=False)
    batch = next(b.global_arrays(0))
    arr = batch["input_ids"]
    assert arr.shape == (16, 8)
    # batch dim split over the 8-way data axis
    assert len(arr.sharding.device_set) == 8
    db = arr.sharding.shard_shape(arr.shape)
    assert db == (2, 8)


def test_indivisible_global_batch_rejected():
    mesh = build_mesh(MeshConfig())
    with pytest.raises(ValueError):
        ShardedBatcher(_dataset(16), 6, mesh, process_index=0, process_count=4)


def test_prefetch_iterator_values_and_exceptions():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    assert list(PrefetchIterator(iter(range(7)), depth=2)) == list(range(7))

    def boom():
        yield 1
        raise RuntimeError("producer failed")

    it = PrefetchIterator(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer failed"):
        next(it)


def test_prefetch_iterator_close_stops_thread():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(10_000)), depth=2)
    assert next(it) == 0
    thread = it._thread
    it.close()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_prefetch_iterator_gc_reclaims_thread():
    """Dropping the iterator without close() must stop the producer (the
    thread target must not keep the wrapper alive)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(10_000)), depth=2)
    assert next(it) == 0
    thread = it._thread
    del it
    gc.collect()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_prefetch_iterator_exhaustion_is_sticky():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    it = PrefetchIterator(iter(range(3)), depth=2)
    assert list(it) == [0, 1, 2]
    assert next(it, "default") == "default"   # must not block
    it2 = PrefetchIterator(iter(range(3)), depth=2)
    it2.close()
    assert next(it2, None) is None


def _ragged_dataset(n, width=64, seed=0):
    rng = np.random.RandomState(seed)
    ids = np.zeros((n, width), np.int32)
    mask = np.zeros((n, width), np.int32)
    lengths = rng.randint(4, width + 1, size=n)
    for i, L in enumerate(lengths):
        ids[i, :L] = rng.randint(5, 100, size=L)
        mask[i, :L] = 1
    labels = rng.randint(0, 2, size=n).astype(np.int32)
    return ArrayDataset({"input_ids": ids, "attention_mask": mask,
                         "labels": labels}), lengths


def test_bucketing_trims_to_batch_bucket():
    mesh = build_mesh(MeshConfig())
    ds, lengths = _ragged_dataset(64)
    b = ShardedBatcher(ds, 8, mesh, shuffle=False, seed=0,
                       bucket_sizes=[16, 32, 48, 64],
                       process_index=0, process_count=1)
    for s, batch in enumerate(b.local_batches(0)):
        lo = s * 8
        expect_max = lengths[lo:lo + 8].max()
        bucket = min(bkt for bkt in [16, 32, 48, 64] if bkt >= expect_max)
        assert batch["input_ids"].shape == (8, bucket)
        assert batch["attention_mask"].shape == (8, bucket)
        assert batch["labels"].shape == (8,)          # non-token column kept
        # no real token lost
        assert batch["attention_mask"].sum() == sum(lengths[lo:lo + 8])


def test_bucketing_hosts_agree_on_widths():
    mesh = build_mesh(MeshConfig())
    ds, _ = _ragged_dataset(64)
    kw = dict(shuffle=True, seed=3, bucket_sizes=[16, 32, 64], process_count=2)
    b0 = ShardedBatcher(ds, 8, mesh, process_index=0, **kw)
    b1 = ShardedBatcher(ds, 8, mesh, process_index=1, **kw)
    for x, y in zip(b0.local_batches(1), b1.local_batches(1)):
        assert x["input_ids"].shape == y["input_ids"].shape
        # shards are disjoint halves of the same global batch
        assert not np.array_equal(x["input_ids"], y["input_ids"])


def test_bucketing_window_sort_is_permutation():
    mesh = build_mesh(MeshConfig())
    ds, lengths = _ragged_dataset(128)
    b = ShardedBatcher(ds, 8, mesh, shuffle=True, seed=0,
                       bucket_sizes=[16, 32, 64], bucket_window=4,
                       process_index=0, process_count=1)
    seen = []
    for batch in b.local_batches(0):
        seen.extend(batch["input_ids"].sum(axis=1).tolist())
    assert len(seen) == (128 // 8) * 8
    # within a 4-batch window, batches are length-ordered → less padding:
    # average batch bucket must be below the no-sort worst case
    widths = [batch["input_ids"].shape[1] for batch in b.local_batches(0)]
    assert np.mean(widths) < 64


def test_bucketing_eval_tail_buckets_from_valid_rows_only():
    """drop_remainder=False pads the tail with dataset row 0; the bucket
    width must derive from the REAL rows, and the valid mask must still
    mark exactly the real ones after trimming."""
    ds, lengths = _ragged_dataset(20)
    # make the pad source (row 0) the longest row: a naive bucket choice
    # over all rows would widen the tail batch because of padding copies
    ds.columns["attention_mask"][0, :] = 1
    ds.columns["input_ids"][0, :] = 7
    lengths[0] = 64
    mesh = build_mesh(MeshConfig())
    b = ShardedBatcher(ds, 8, mesh, shuffle=False, drop_remainder=False,
                       bucket_sizes=[16, 32, 48, 64],
                       process_index=0, process_count=1)
    batches = list(b.local_batches(0))
    assert len(batches) == 3
    tail = batches[-1]
    assert tail["valid"].sum() == 4              # 20 = 8+8+4
    real_max = lengths[16:20].max()
    bucket = min(bkt for bkt in [16, 32, 48, 64] if bkt >= real_max)
    assert tail["input_ids"].shape == (8, bucket)
    # every real token of the real rows survived the trim
    assert tail["attention_mask"][:4].sum() == lengths[16:20].sum()


def test_bucketing_rejects_widths_indivisible_by_seq_axis(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, sp=2), devices=devices8)
    ds, _ = _ragged_dataset(16)
    with pytest.raises(ValueError, match="seq axis"):
        ShardedBatcher(ds, 8, mesh, bucket_sizes=[15, 32],
                       process_index=0, process_count=1)


def test_bucketing_seq2seq_independent_widths():
    mesh = build_mesh(MeshConfig())
    rng = np.random.RandomState(0)
    n, ew, dw = 16, 64, 32
    enc_mask = np.zeros((n, ew), np.int32); enc_mask[:, :10] = 1
    dec_mask = np.zeros((n, dw), np.int32); dec_mask[:, :5] = 1
    ds = ArrayDataset({
        "input_ids": rng.randint(1, 50, (n, ew)).astype(np.int32),
        "attention_mask": enc_mask,
        "decoder_input_ids": rng.randint(1, 50, (n, dw)).astype(np.int32),
        "decoder_attention_mask": dec_mask,
        "labels": rng.randint(1, 50, (n, dw)).astype(np.int32),
    })
    b = ShardedBatcher(ds, 8, mesh, shuffle=False,
                       bucket_sizes=[8, 16, 32, 64],
                       process_index=0, process_count=1)
    batch = next(iter(b.local_batches(0)))
    assert batch["input_ids"].shape == (8, 16)           # 10 → bucket 16
    assert batch["decoder_input_ids"].shape == (8, 8)    # 5 → bucket 8
    assert batch["labels"].shape == (8, 8)               # decoder width group


def test_vendored_reviews_loads():
    # the in-repo authored corpus (data/vendored/README.md) resolves by
    # name, both splits, balanced labels, natural multi-sentence text
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        load_text_classification,
    )
    for split, n in (("train", 4000), ("test", 1000)):
        texts, labels = load_text_classification("vendored_reviews", split)
        assert len(texts) == len(labels) == n
        assert set(labels) == {0, 1}
        assert sum(labels) == n // 2
        assert all("." in t and len(t.split()) >= 8 for t in texts[:50])


def test_packed_lm_corpus_zero_padding():
    """packed=True: EOS-joined documents chunked into completely full
    rows — zero pad tokens, token stream preserved in order, tail
    dropped. The TPU pretraining layout (every MXU cycle on real
    tokens vs ~50% pad at IMDb-like lengths)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        WordHashTokenizer,
    )

    tok = WordHashTokenizer(vocab_size=512)
    texts = [f"doc {i} " + "word " * (5 + i % 7) for i in range(40)]
    L = 32
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=L, packed=True,
                                    eos_token_id=3)
    ids = ds.columns["input_ids"]
    am = ds.columns["attention_mask"]
    labels = ds.columns["labels"]
    assert ids.shape[1] == L and ids.shape[0] >= 2
    # ZERO padding anywhere
    assert am.all() and (labels != -100).all()
    np.testing.assert_array_equal(labels, ids)
    # the flat stream equals the per-doc tokenization joined by EOS
    want = []
    for t in texts:
        enc = tok([t], truncation=False, padding="longest",
                  add_special_tokens=False)
        m = np.asarray(enc["attention_mask"][0]) > 0
        want.extend(int(x) for x in np.asarray(enc["input_ids"][0])[m])
        want.append(3)
    np.testing.assert_array_equal(ids.reshape(-1),
                                  np.asarray(want[: ids.size], np.int32))
    # unpacked comparison: same corpus wastes most positions on padding
    dense = ArrayDataset.from_lm_texts(tok, texts, max_length=L)
    pad_frac = 1.0 - dense.columns["attention_mask"].mean()
    assert pad_frac > 0.4


def test_packed_corpus_too_small_raises():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        WordHashTokenizer,
    )

    tok = WordHashTokenizer(vocab_size=512)
    with pytest.raises(ValueError, match="packed"):
        ArrayDataset.from_lm_texts(tok, ["two words"], max_length=512,
                                   packed=True, eos_token_id=3)
    # an out-of-vocab separator (e.g. GPT-2's default eos 50256 on a
    # small-vocab test config) must fail loudly, not train to NaN
    with pytest.raises(ValueError, match="outside the"):
        ArrayDataset.from_lm_texts(tok, ["some words here"] * 20,
                                   max_length=16, packed=True,
                                   eos_token_id=50256)
