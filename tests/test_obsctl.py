"""obsctl / cross-host report tests (ISSUE 4): synthetic 3-host
telemetry (one straggler, one anomaly) merges into one deterministic
report — identical across every input ordering — that passes its own
schema validator; the CLI round-trips it; host identity comes from the
events, not the directory layout.
"""

import itertools
import json
import os
import subprocess
import sys

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
    build_report,
    find_event_files,
    render_text,
    validate_report,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBSCTL = os.path.join(_REPO, "scripts", "obsctl.py")


def _ev(host, t, etype, **fields):
    return {"v": 1, "t": t, "host": host, "pid": 100 + host,
            "type": etype, **fields}


def _write(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


@pytest.fixture()
def three_hosts(tmp_path):
    """Host 0 (rank 0: run header, straggler timeline, serve report),
    host 1 (healthy), host 2 (the straggler, with one anomaly)."""
    step_times = {0: 0.10, 1: 0.11, 2: 0.19}
    dirs = []
    for host in range(3):
        events = []
        t = 1000.0 + host
        if host == 0:
            events.append(_ev(0, t, "run", argv=["train.py", "--epochs=2"]))
        for i in range(4):
            t += 1
            events.append(_ev(host, t, "metric", name="train/step_time_s",
                              value=step_times[host], step=i))
            events.append(_ev(host, t, "metric",
                              name="train/samples_per_sec",
                              value=100.0 / step_times[host], step=i))
            events.append(_ev(host, t, "metric", name="train/mfu",
                              value=0.31 - 0.01 * host, step=i))
        events.append(_ev(host, t + 1, "compile",
                          event="/jax/pjit/compile", dur=2.0,
                          count=5 + host, cum=11.5))
        events.append(_ev(host, t + 2, "heartbeat", uptime=60.0,
                          progress=400, progress_age=0.5))
        events.append(_ev(host, t + 3, "memory", device="tpu:0",
                          stats={"peak_bytes_in_use": 9 << 30,
                                 "bytes_limit": 16 << 30}))
        if host == 0:
            for epoch in range(2):
                events.append(_ev(0, t + 4 + epoch, "metric",
                                  name="train/step_time_hosts_mean",
                                  value=0.133, step=epoch,
                                  args={"n_hosts": 3, "min": 0.10,
                                        "max": 0.19, "mean": 0.133,
                                        "straggler_ratio": 1.425,
                                        "argmax": 2}))
            events.append(_ev(0, t + 7, "anomaly", name="straggler",
                              message="host 2 is a persistent "
                                      "straggler: step-time ratio 1.425 "
                                      "> 1.1 for 2 consecutive epochs "
                                      "(epoch 1)",
                              step=1, slow_host=2))
            events.append(_ev(0, t + 8, "serve", event="report",
                              requests=48, tokens=512, iterations=90,
                              preemptions=2, peak_waiting_depth=7,
                              kv_peak_utilization=0.83,
                              ttft_p50_s=0.02, ttft_p95_s=0.05,
                              ttft_p99_s=0.07, e2e_p50_s=0.4,
                              e2e_p95_s=0.9, e2e_p99_s=1.2,
                              speculate_k=4, acceptance_rate=0.72,
                              prefix_cache=True, cache_hit_rate=0.9,
                              blocks_shared_peak=40,
                              queue_wait_p50_s=0.1,
                              queue_wait_p99_s=0.8,
                              queue_time_frac=0.2,
                              decode_time_frac=0.7,
                              preempted_time_frac=0.05,
                              overhead_time_frac=0.05,
                              tp=2,
                              kv_pool_bytes_per_device=1 << 20,
                              replicas=2, placement="least_loaded",
                              replica_load_imbalance=1.2,
                              slo_attainment=0.97,
                              arrival_backlog_peak=3,
                              swap_policy="always", swap_outs=5,
                              swap_ins=4, swap_bytes=1 << 19,
                              restore_s=0.02,
                              recompute_tokens_avoided=320,
                              host_tier_hits=12,
                              host_tier_hit_rate=0.92,
                              roles="prefill:1,decode:1",
                              migrations=6, migration_bytes=1 << 18,
                              migration_restore_s=0.015,
                              disagg_slo_attainment=0.96))
            # fleet tracing (ISSUE 19): the stitch summary is a
            # SEPARATE event after the report — _serve_summary must
            # overlay its counters onto the scalar surface
            events.append(_ev(0, t + 9, "serve", event="trace_stitch",
                              traces=48, complete_traces=48,
                              trace_stitch_failures=0,
                              transport_hop_s_p99=0.004))
        if host == 2:
            events.append(_ev(2, t + 9, "anomaly", name="step_time_spike",
                              message="step time 0.9s exceeds rolling "
                                      "median 0.19s", step=3,
                              evidence="flight_3.jsonl"))
        d = tmp_path / f"host{host}"
        _write(str(d / "events.jsonl"), events)
        dirs.append(str(d))
    return dirs


def test_merged_report_structure(three_hosts):
    report = build_report(three_hosts)
    assert validate_report(report) == []
    assert sorted(report["hosts"]) == ["0", "1", "2"]
    assert report["run"]["n_hosts"] == 3
    assert report["run"]["argv"] == ["train.py", "--epochs=2"]
    # the straggler is visible twice: per-epoch timeline + host section
    timeline = report["straggler_timeline"]
    assert len(timeline) == 2
    assert all(row["argmax_host"] == 2 for row in timeline)
    assert timeline[0]["straggler_ratio"] == pytest.approx(1.425)
    # host 2's step-time distribution sits above host 0's
    assert (report["hosts"]["2"]["step_time_s"]["p50"]
            > report["hosts"]["0"]["step_time_s"]["p50"])
    # the anomaly index carries both incidents: host 0's straggler
    # alert (epoch 1) and host 2's local spike
    assert len(report["anomaly_index"]) == 2
    assert {(a["host"], a["name"]) for a in report["anomaly_index"]} \
        == {(0, "straggler"), (2, "step_time_spike")}
    assert report["hosts"]["2"]["anomalies"] == 1
    assert report["hosts"]["0"]["anomalies"] == 1
    # serving SLO summary came from the engine's report event
    assert report["serve"]["requests"] == 48
    assert report["serve"]["ttft_p99_s"] == pytest.approx(0.07)
    assert report["serve"]["peak_waiting_depth"] == 7
    # compile + memory rollups
    assert report["hosts"]["1"]["compile"] == {"count": 6, "cum_s": 11.5}
    assert report["hosts"]["0"]["memory"]["peak_bytes_in_use"] == 9 << 30
    assert report["errors"] == []


def test_report_deterministic_across_input_orderings(three_hosts):
    reference = build_report(three_hosts)
    for perm in itertools.permutations(three_hosts):
        assert build_report(list(perm)) == reference
    # byte-identical JSON, not just dict-equal
    blob = json.dumps(reference, sort_keys=True)
    for perm in itertools.permutations(three_hosts):
        assert json.dumps(build_report(list(perm)), sort_keys=True) == blob


def test_parent_dir_discovers_host_subdirs(three_hosts, tmp_path):
    assert len(find_event_files([str(tmp_path)])) == 3
    report = build_report([str(tmp_path)])
    assert report == build_report(three_hosts)


def test_schema_errors_reported_not_fatal(three_hosts, tmp_path):
    bad = tmp_path / "host3"
    _write(str(bad / "events.jsonl"),
           [_ev(3, 2000.0, "metric", value=1.0),     # missing name
            _ev(3, 2001.0, "metric", name="ok", value=2.0)])
    report = build_report(three_hosts + [str(bad)])
    assert validate_report(report) == []
    assert sorted(report["hosts"]) == ["0", "1", "2", "3"]
    assert report["hosts"]["3"]["events"] == 1       # valid line kept
    assert any("missing field 'name'" in e for e in report["errors"])


def test_render_text_readable(three_hosts):
    text = render_text(build_report(three_hosts))
    assert "host 2:" in text and "1 anomalies" in text
    assert "straggler timeline:" in text and "host 2 slow" in text
    assert "serve: 48 requests" in text
    assert "step time: p50" in text


def test_cli_report_json_and_text(three_hosts, tmp_path):
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "report", *three_hosts,
         "-o", str(out)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    assert proc.returncode == 0, proc.stderr
    stdout_report = json.loads(proc.stdout)
    assert validate_report(stdout_report) == []
    assert json.loads(out.read_text()) == stdout_report
    text = subprocess.run(
        [sys.executable, _OBSCTL, "report", "--text", *three_hosts],
        stdout=subprocess.PIPE, text=True, cwd=_REPO)
    assert "straggler timeline:" in text.stdout


def test_cli_runs_without_jax():
    """The stdlib contract: obsctl must work on jax-less boxes.
    Converted (ISSUE 15) from a subprocess poison run to graftlint
    R1's static import-time reachability — complete over every import
    edge, where the subprocess only proved the paths this test
    happened to execute. Runtime subprocess smokes remain slow-tier
    (test_cli_subprocess_smoke_without_jax below, and the validator
    one in test_telemetry_schema)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
        load_project,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.rules import (
        check_r1,
        r1_reachability,
    )

    project = load_project(_REPO)
    assert check_r1(project) == []
    assert "scripts/obsctl.py" in r1_reachability(project)


def test_cli_report_rejects_empty_input(tmp_path):
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "report", str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    assert proc.returncode == 1
    assert "no events.jsonl" in proc.stderr


def test_allgather_duplicates_collapse_to_one_incident(tmp_path):
    """Under HSTD_TELEMETRY_ALL_HOSTS every host emits the SAME
    allgathered straggler metric and the same collective-derived
    anomaly; the merge must report one timeline row per epoch and one
    incident, not N copies."""
    args = {"n_hosts": 2, "min": 0.1, "max": 0.2, "mean": 0.15,
            "straggler_ratio": 1.33, "argmax": 1}
    for host in range(2):
        _write(str(tmp_path / f"h{host}" / "events.jsonl"), [
            _ev(host, 1000.0 + host, "metric",
                name="train/step_time_hosts_mean", value=0.15, step=0,
                args=args),
            _ev(host, 1001.0 + host, "anomaly", name="straggler",
                message="host 1 is a persistent straggler", step=0,
                slow_host=1),
        ])
    report = build_report([str(tmp_path / "h0"), str(tmp_path / "h1")])
    assert len(report["straggler_timeline"]) == 1
    assert len(report["anomaly_index"]) == 1
    assert report["anomaly_index"][0]["host"] == 0   # lowest host kept


def test_all_hosts_event_files_produced_and_merged(tmp_path, monkeypatch):
    """HSTD_TELEMETRY_ALL_HOSTS=1: a non-zero host writes its OWN
    events.host<K>.jsonl (no shared-file append interleaving), and the
    report merges it — the path that makes N-host reports real."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs

    monkeypatch.setenv("HSTD_TELEMETRY_ALL_HOSTS", "1")
    out = tmp_path / "t"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        obs.set_host(1, 2)
        obs.scalar("train/step_time_s", 0.25, 3)
        obs.flush()
    finally:
        obs.reset()
    assert (out / "events.host1.jsonl").exists()
    assert not (out / "events.jsonl").exists()   # host 0 never wrote
    assert find_event_files([str(out)]) == [str(out /
                                                "events.host1.jsonl")]
    report = build_report([str(out)])
    assert list(report["hosts"]) == ["1"]
    assert report["hosts"]["1"]["step_time_s"]["count"] == 1


def test_default_demotion_still_closes_nonzero_hosts(tmp_path):
    """Without the all-hosts knob, the PR 1 discipline holds: a host
    demoted from the rank-0 guess writes nothing."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs

    out = tmp_path / "t"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        obs.set_host(1, 2)
        obs.scalar("train/loss", 1.0, 0)
        obs.flush()
    finally:
        obs.reset()
    assert find_event_files([str(out)]) == []


def test_cli_validate_subcommand(three_hosts):
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "validate", three_hosts[0]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=_REPO)
    assert proc.returncode == 0, proc.stdout


# -- report diffing (ISSUE 5: `obsctl diff`) ---------------------------------

def _perturbed(report, step_p50=None, decode_tps=None, anomalies=0):
    import copy

    doc = copy.deepcopy(report)
    if step_p50 is not None:
        for sec in doc["hosts"].values():
            if sec.get("step_time_s"):
                sec["step_time_s"]["p50"] = step_p50
    if decode_tps is not None:
        doc.setdefault("serve", {})["decode_tokens_per_sec"] = decode_tps
    for i in range(anomalies):
        doc["anomaly_index"].append(
            {"t": 2000.0 + i, "host": 1, "name": "nan_loss", "step": 9,
             "message": "loss is NaN", "evidence": None})
    return doc


def test_diff_reports_flags_worse_directions(three_hosts):
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    same = diff_reports(base, base, threshold_pct=5.0)
    assert same["regressions"] == []
    # identical inputs -> byte-identical output (the determinism the
    # one-command triage relies on)
    a = json.dumps(diff_reports(base, base, 5.0), sort_keys=True)
    b = json.dumps(diff_reports(base, base, 5.0), sort_keys=True)
    assert a == b

    worse = _perturbed(base, step_p50=0.30, anomalies=1)
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "step_time_p50_s" in d["regressions"]
    assert "anomalies" in d["regressions"]        # count metric: any up
    assert d["metrics"]["step_time_p50_s"]["regressed"] is True
    # the same move in the BETTER direction is not a regression
    better = _perturbed(base, step_p50=0.01)
    assert "step_time_p50_s" not in diff_reports(
        base, better, 5.0)["regressions"]
    # under the threshold: no flag
    slight = _perturbed(base, step_p50=0.134)     # ~+3% off 0.13
    assert "step_time_p50_s" not in diff_reports(
        base, slight, 5.0)["regressions"]


def test_diff_zero_baseline_worsening_still_regresses(three_hosts):
    """A ratio metric with a 0 baseline has no percentage, but ANY
    worsening from it must flag (compile_cum_s 0.0 under a warm
    persistent cache -> recompiles in the candidate)."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    for sec in base["hosts"].values():
        sec["compile"]["cum_s"] = 0.0
    worse = copy.deepcopy(base)
    for sec in worse["hosts"].values():
        sec["compile"]["cum_s"] = 40.0
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "compile_cum_s" in d["regressions"]
    assert d["metrics"]["compile_cum_s"]["pct"] is None
    # and the better direction from 0 never flags
    assert "compile_cum_s" not in diff_reports(
        worse, base, 5.0)["regressions"]


def test_diff_acceptance_rate_is_a_ratio_metric(three_hosts):
    """ISSUE 6: `serve/acceptance_rate` diffs as a ratio metric whose
    worse direction is DOWN (a draft/target drift or broken verify
    path collapses acceptance first), with the standard zero-baseline
    and threshold rules."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["acceptance_rate"] == pytest.approx(0.72)
    worse = copy.deepcopy(base)
    worse["serve"]["acceptance_rate"] = 0.31
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_acceptance_rate" in d["regressions"]
    assert d["metrics"]["serve_acceptance_rate"]["worse_direction"] == "down"
    # the better direction never flags; a sub-threshold dip neither
    assert "serve_acceptance_rate" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["acceptance_rate"] = 0.70      # ~-2.8%
    assert "serve_acceptance_rate" not in diff_reports(
        base, slight, 5.0)["regressions"]


def test_diff_cache_hit_rate_is_a_ratio_metric(three_hosts):
    """ISSUE 8: `serve/cache_hit_rate` diffs as a ratio metric whose
    worse direction is DOWN — a broken chain hash, over-eager eviction,
    or a trace drifting off its template all read as the prefix cache
    silently going cold (and TTFT regressing with it)."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["cache_hit_rate"] == pytest.approx(0.9)
    worse = copy.deepcopy(base)
    worse["serve"]["cache_hit_rate"] = 0.2
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_cache_hit_rate" in d["regressions"]
    assert d["metrics"]["serve_cache_hit_rate"]["worse_direction"] == "down"
    # better direction never flags; a sub-threshold dip neither
    assert "serve_cache_hit_rate" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["cache_hit_rate"] = 0.88       # ~-2.2%
    assert "serve_cache_hit_rate" not in diff_reports(
        base, slight, 5.0)["regressions"]


def test_diff_queue_wait_and_preempted_frac_are_up_worse(three_hosts):
    """ISSUE 10: `serve_queue_wait_p99_s` and
    `serve_preempted_time_frac` diff as time/ratio metrics whose worse
    direction is UP — an admission-policy or pool-sizing regression
    shows up in the lifecycle decomposition before the aggregate e2e
    percentiles move. Standard threshold + zero-baseline rules."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["queue_wait_p99_s"] == pytest.approx(0.8)
    worse = copy.deepcopy(base)
    worse["serve"]["queue_wait_p99_s"] = 2.4
    worse["serve"]["preempted_time_frac"] = 0.3
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_queue_wait_p99_s" in d["regressions"]
    assert "serve_preempted_time_frac" in d["regressions"]
    assert d["metrics"]["serve_queue_wait_p99_s"]["worse_direction"] \
        == "up"
    # the better direction never flags; a sub-threshold drift neither
    assert not {"serve_queue_wait_p99_s", "serve_preempted_time_frac"} \
        & set(diff_reports(worse, base, 5.0)["regressions"])
    slight = copy.deepcopy(base)
    slight["serve"]["queue_wait_p99_s"] = 0.82      # +2.5%
    assert "serve_queue_wait_p99_s" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline: a healthy run preempts nothing, so ANY preempted
    # time appearing must flag even though the pct is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["preempted_time_frac"] = 0.0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["preempted_time_frac"] = 0.08
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_preempted_time_frac" in d0["regressions"]
    assert d0["metrics"]["serve_preempted_time_frac"]["pct"] is None


def test_diff_overhead_time_frac_is_a_ratio_metric(three_hosts):
    """ISSUE 12: `serve_overhead_time_frac` diffs as a ratio metric
    whose worse direction is UP — the dispatch-ahead loop exists to
    shrink the host-overhead share, so it creeping back up (a new
    sync point on the hot path, a flush storm) must flag. Standard
    threshold + zero-baseline rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["overhead_time_frac"] == pytest.approx(0.05)
    worse = copy.deepcopy(base)
    worse["serve"]["overhead_time_frac"] = 0.4
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_overhead_time_frac" in d["regressions"]
    assert d["metrics"]["serve_overhead_time_frac"]["worse_direction"] \
        == "up"
    # the better direction never flags; a sub-threshold drift neither
    assert "serve_overhead_time_frac" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["overhead_time_frac"] = 0.051   # +2%
    assert "serve_overhead_time_frac" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline: a fully-overlapped run hides ALL host overhead,
    # so any overhead reappearing must flag despite pct undefined
    zero = copy.deepcopy(base)
    zero["serve"]["overhead_time_frac"] = 0.0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["overhead_time_frac"] = 0.12
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_overhead_time_frac" in d0["regressions"]
    assert d0["metrics"]["serve_overhead_time_frac"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["overhead_time_frac"] = "hidden"
    missing = copy.deepcopy(base)
    del missing["serve"]["overhead_time_frac"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_overhead_time_frac" in d["skipped"]
        assert "serve_overhead_time_frac" not in d["regressions"]


def test_diff_replica_load_imbalance_is_ratio_metric(three_hosts):
    """ISSUE 14: `serve_replica_load_imbalance` (max/mean requests
    served per replica) diffs as a ratio metric whose worse direction
    is UP — a broken placement policy, an affinity index starving load
    balance, or a drained replica nobody restarted all show up here
    before throughput or the tail moves. Standard threshold +
    zero-baseline rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["replica_load_imbalance"] == pytest.approx(1.2)
    worse = copy.deepcopy(base)
    worse["serve"]["replica_load_imbalance"] = 1.9   # one hot replica
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_replica_load_imbalance" in d["regressions"]
    assert d["metrics"]["serve_replica_load_imbalance"][
        "worse_direction"] == "up"
    # evening out never flags; nor does a sub-threshold drift
    assert "serve_replica_load_imbalance" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["replica_load_imbalance"] = 1.22   # < +5%
    assert "serve_replica_load_imbalance" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (degenerate report): imbalance appearing must
    # still flag even though the percentage is undefined — shared rule
    zero = copy.deepcopy(base)
    zero["serve"]["replica_load_imbalance"] = 0.0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["replica_load_imbalance"] = 1.4
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_replica_load_imbalance" in d0["regressions"]
    assert d0["metrics"]["serve_replica_load_imbalance"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["replica_load_imbalance"] = "lopsided"
    missing = copy.deepcopy(base)
    del missing["serve"]["replica_load_imbalance"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_replica_load_imbalance" in d["skipped"]
        assert "serve_replica_load_imbalance" not in d["regressions"]


def test_diff_kv_pool_bytes_per_device_is_bytes_metric(three_hosts):
    """ISSUE 13: `serve_kv_pool_bytes_per_device` diffs as a bytes
    metric whose worse direction is UP — a lost heads-sharding (pools
    silently replicated), a dropped tp knob, or an fp pool where int8
    was configured all show up as per-chip pool bytes growing for the
    same capacity. Standard threshold + zero-baseline rules, poison
    rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["kv_pool_bytes_per_device"] == 1 << 20
    worse = copy.deepcopy(base)
    worse["serve"]["kv_pool_bytes_per_device"] = 2 << 20   # un-sharded
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_kv_pool_bytes_per_device" in d["regressions"]
    assert d["metrics"]["serve_kv_pool_bytes_per_device"][
        "worse_direction"] == "up"
    # the better direction (sharding landed, bytes halved) never flags;
    # nor does a sub-threshold drift
    assert "serve_kv_pool_bytes_per_device" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["kv_pool_bytes_per_device"] = int(1.02 * (1 << 20))
    assert "serve_kv_pool_bytes_per_device" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (unsized pool): bytes appearing must still flag
    # even though the percentage is undefined — the shared rule
    zero = copy.deepcopy(base)
    zero["serve"]["kv_pool_bytes_per_device"] = 0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["kv_pool_bytes_per_device"] = 1 << 18
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_kv_pool_bytes_per_device" in d0["regressions"]
    assert d0["metrics"]["serve_kv_pool_bytes_per_device"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["kv_pool_bytes_per_device"] = "one chip's worth"
    missing = copy.deepcopy(base)
    del missing["serve"]["kv_pool_bytes_per_device"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_kv_pool_bytes_per_device" in d["skipped"]
        assert "serve_kv_pool_bytes_per_device" not in d["regressions"]


def test_diff_slo_attainment_is_down_worse_ratio(three_hosts):
    """ISSUE 16: `serve_slo_attainment` (deadline-met fraction from an
    open-loop run's report event) diffs as a ratio metric whose worse
    direction is DOWN — goodput is the currency, so attainment eroding
    under the same offered load is THE serving regression, ahead of
    any single latency percentile moving. Standard threshold rules,
    poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["slo_attainment"] == pytest.approx(0.97)
    worse = copy.deepcopy(base)
    worse["serve"]["slo_attainment"] = 0.80
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_slo_attainment" in d["regressions"]
    assert d["metrics"]["serve_slo_attainment"][
        "worse_direction"] == "down"
    # attainment improving never flags; nor does a sub-threshold dip
    assert "serve_slo_attainment" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["slo_attainment"] = 0.95       # ~-2.1%
    assert "serve_slo_attainment" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["slo_attainment"] = "mostly"
    missing = copy.deepcopy(base)
    del missing["serve"]["slo_attainment"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_slo_attainment" in d["skipped"]
        assert "serve_slo_attainment" not in d["regressions"]


def test_diff_arrival_backlog_peak_is_count_metric(three_hosts):
    """ISSUE 16: `serve_arrival_backlog_peak` (deepest arrived-but-
    unadmitted queue an open-loop run saw) diffs as a count metric
    whose worse direction is UP — admission slowing down shows up here
    BEFORE attainment falls, the leading indicator of the capacity
    knee. Standard threshold + zero-baseline rules, poison rows
    skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["arrival_backlog_peak"] == 3
    worse = copy.deepcopy(base)
    worse["serve"]["arrival_backlog_peak"] = 11
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_arrival_backlog_peak" in d["regressions"]
    assert d["metrics"]["serve_arrival_backlog_peak"][
        "worse_direction"] == "up"
    # backlog shrinking never flags; nor does a sub-threshold drift
    assert "serve_arrival_backlog_peak" not in diff_reports(
        worse, base, 5.0)["regressions"]
    # zero baseline (underloaded run, backlog never formed): a backlog
    # appearing must still flag though the percentage is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["arrival_backlog_peak"] = 0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["arrival_backlog_peak"] = 6
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_arrival_backlog_peak" in d0["regressions"]
    assert d0["metrics"]["serve_arrival_backlog_peak"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["arrival_backlog_peak"] = "deep"
    missing = copy.deepcopy(base)
    del missing["serve"]["arrival_backlog_peak"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_arrival_backlog_peak" in d["skipped"]
        assert "serve_arrival_backlog_peak" not in d["regressions"]


def test_diff_swap_bytes_is_up_worse(three_hosts):
    """ISSUE 17: `serve_swap_bytes` (host RAM moved by the KV spill
    tier) diffs as a bytes metric whose worse direction is UP — more
    traffic over the host boundary for the same trace means the
    preemption economics shifted (shrunken pool, lost prefix sharing,
    or a mis-tuned budget forcing churn). Standard threshold +
    zero-baseline rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["swap_bytes"] == 1 << 19
    worse = copy.deepcopy(base)
    worse["serve"]["swap_bytes"] = 4 << 19       # tier thrashing
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_swap_bytes" in d["regressions"]
    assert d["metrics"]["serve_swap_bytes"]["worse_direction"] == "up"
    # less host traffic never flags; nor does a sub-threshold drift
    assert "serve_swap_bytes" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["swap_bytes"] = int(1.02 * (1 << 19))
    assert "serve_swap_bytes" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (swap=never run, tier idle): bytes appearing must
    # still flag even though the percentage is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["swap_bytes"] = 0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["swap_bytes"] = 1 << 16
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_swap_bytes" in d0["regressions"]
    assert d0["metrics"]["serve_swap_bytes"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["swap_bytes"] = "a lot"
    missing = copy.deepcopy(base)
    del missing["serve"]["swap_bytes"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_swap_bytes" in d["skipped"]
        assert "serve_swap_bytes" not in d["regressions"]


def test_diff_host_tier_hit_rate_is_down_worse_ratio(three_hosts):
    """ISSUE 17: `serve_host_tier_hit_rate` (fraction of prefix-cache
    probes revived from the demoted host tier) diffs as a ratio metric
    whose worse direction is DOWN — the tier eroding means demoted
    prefixes are being evicted (budget too small) or never matched
    (demotion ordering broke), and those misses come back as re-prefill
    FLOPs. Standard threshold rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["host_tier_hit_rate"] == pytest.approx(0.92)
    worse = copy.deepcopy(base)
    worse["serve"]["host_tier_hit_rate"] = 0.55
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_host_tier_hit_rate" in d["regressions"]
    assert d["metrics"]["serve_host_tier_hit_rate"][
        "worse_direction"] == "down"
    # the tier catching more never flags; nor does a sub-threshold dip
    assert "serve_host_tier_hit_rate" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["host_tier_hit_rate"] = 0.90   # ~-2.2%
    assert "serve_host_tier_hit_rate" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["host_tier_hit_rate"] = "usually"
    missing = copy.deepcopy(base)
    del missing["serve"]["host_tier_hit_rate"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_host_tier_hit_rate" in d["skipped"]
        assert "serve_host_tier_hit_rate" not in d["regressions"]


def test_diff_migration_bytes_is_up_worse(three_hosts):
    """ISSUE 18: `serve_migration_bytes` (KV bytes moved between
    engines by the transport) diffs as a bytes metric whose worse
    direction is UP — more cross-engine traffic for the same trace
    means the harvest loop or drain policy started moving work a
    steady fleet would have left in place. Standard threshold +
    zero-baseline rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["migration_bytes"] == 1 << 18
    worse = copy.deepcopy(base)
    worse["serve"]["migration_bytes"] = 4 << 18   # transport thrashing
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_migration_bytes" in d["regressions"]
    assert d["metrics"]["serve_migration_bytes"][
        "worse_direction"] == "up"
    # less transport traffic never flags; nor does sub-threshold drift
    assert "serve_migration_bytes" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["migration_bytes"] = int(1.02 * (1 << 18))
    assert "serve_migration_bytes" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (mixed fleet, no drains — transport idle): bytes
    # appearing must still flag though the percentage is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["migration_bytes"] = 0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["migration_bytes"] = 1 << 16
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_migration_bytes" in d0["regressions"]
    assert d0["metrics"]["serve_migration_bytes"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["migration_bytes"] = "heavy"
    missing = copy.deepcopy(base)
    del missing["serve"]["migration_bytes"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_migration_bytes" in d["skipped"]
        assert "serve_migration_bytes" not in d["regressions"]


def test_diff_disagg_slo_attainment_is_down_worse_ratio(three_hosts):
    """ISSUE 18: `serve_disagg_slo_attainment` (deadline attainment of
    the disaggregated fleet) diffs as a ratio metric whose worse
    direction is DOWN — the split fleet's headline eroding means role
    separation stopped paying (stalled handoffs, a starved decode
    side, migration overhead eating the TTFT win). Standard threshold
    rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["disagg_slo_attainment"] == pytest.approx(0.96)
    worse = copy.deepcopy(base)
    worse["serve"]["disagg_slo_attainment"] = 0.6
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_disagg_slo_attainment" in d["regressions"]
    assert d["metrics"]["serve_disagg_slo_attainment"][
        "worse_direction"] == "down"
    # attainment improving never flags; nor does a sub-threshold dip
    assert "serve_disagg_slo_attainment" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["disagg_slo_attainment"] = 0.94   # ~-2.1%
    assert "serve_disagg_slo_attainment" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (fully-missing run): attainment moving OFF zero is
    # the better direction — only drops flag
    zero = copy.deepcopy(base)
    zero["serve"]["disagg_slo_attainment"] = 0.0
    d0 = diff_reports(zero, base, threshold_pct=5.0)
    assert "serve_disagg_slo_attainment" not in d0["regressions"]
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["disagg_slo_attainment"] = "mostly"
    missing = copy.deepcopy(base)
    del missing["serve"]["disagg_slo_attainment"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_disagg_slo_attainment" in d["skipped"]
        assert "serve_disagg_slo_attainment" not in d["regressions"]


def test_diff_trace_stitch_failures_is_zero_baseline_count(three_hosts):
    """ISSUE 19: `serve_trace_stitch_failures` diffs as a count metric
    whose worse direction is UP against an exactly-zero baseline — a
    healthy fleet stitches EVERY traced request, so any failure count
    (a dropped hop's evidence, a torn tail, a stamp regression) flags
    regardless of percentage. The counter reaches the scalar surface
    through the trace_stitch event overlay, proving _serve_summary
    merges the stitch summary onto the report. Poison rows
    skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    # the overlay: trace_stitch is a separate event, yet its counters
    # land next to the report event's SLO figures
    assert base["serve"]["trace_stitch_failures"] == 0
    assert base["serve"]["complete_traces"] == 48
    worse = copy.deepcopy(base)
    worse["serve"]["trace_stitch_failures"] = 2
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_trace_stitch_failures" in d["regressions"]
    assert d["metrics"]["serve_trace_stitch_failures"][
        "worse_direction"] == "up"
    assert d["metrics"]["serve_trace_stitch_failures"]["pct"] is None
    # recovering to zero never flags
    assert "serve_trace_stitch_failures" not in diff_reports(
        worse, base, 5.0)["regressions"]
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["trace_stitch_failures"] = "some"
    missing = copy.deepcopy(base)
    del missing["serve"]["trace_stitch_failures"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_trace_stitch_failures" in d["skipped"]
        assert "serve_trace_stitch_failures" not in d["regressions"]


def test_diff_transport_hop_p99_is_up_worse_ratio(three_hosts):
    """ISSUE 19: `serve_transport_hop_s_p99` (the stitched per-hop
    transport latency tail — extract + wire + restore + destination
    admission) diffs as a ratio metric whose worse direction is UP: a
    serialization slowdown or saturated restore path grows this
    before the fleet TTFT percentiles absorb it. Standard threshold +
    zero-baseline rules, poison rows skip-not-crash."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert base["serve"]["transport_hop_s_p99"] == pytest.approx(0.004)
    worse = copy.deepcopy(base)
    worse["serve"]["transport_hop_s_p99"] = 0.04
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_transport_hop_s_p99" in d["regressions"]
    assert d["metrics"]["serve_transport_hop_s_p99"][
        "worse_direction"] == "up"
    # a faster hop never flags; nor does a sub-threshold drift
    assert "serve_transport_hop_s_p99" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["transport_hop_s_p99"] = 0.00408   # +2%
    assert "serve_transport_hop_s_p99" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (hop never priced — no hot migration): latency
    # appearing must still flag though the percentage is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["transport_hop_s_p99"] = 0.0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["transport_hop_s_p99"] = 0.01
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_transport_hop_s_p99" in d0["regressions"]
    assert d0["metrics"]["serve_transport_hop_s_p99"]["pct"] is None
    # poison rows: mistyped or missing -> skipped, never a crash or a
    # fabricated regression
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["transport_hop_s_p99"] = "slow"
    missing = copy.deepcopy(base)
    del missing["serve"]["transport_hop_s_p99"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_transport_hop_s_p99" in d["skipped"]
        assert "serve_transport_hop_s_p99" not in d["regressions"]


def test_diff_deadline_miss_frac_is_up_worse_ratio(three_hosts):
    """ISSUE 20: `serve_deadline_miss_frac` (fraction of deadline-
    carrying requests whose first token landed past `deadline_s`)
    diffs as a ratio metric whose worse direction is UP — a rising
    miss fraction on the same trace means the admission policy (or a
    capacity regression underneath it) started blowing deadlines the
    previous build met. The field is a `policy=slo` rider, so the
    fixture report does not carry it; both sides get it injected, and
    the poison/missing rows double as the fifo-run case (absent on
    either side -> skipped, never a fabricated regression)."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    assert "deadline_miss_frac" not in base["serve"]   # fifo default
    base = copy.deepcopy(base)
    base["serve"]["deadline_miss_frac"] = 0.05
    worse = copy.deepcopy(base)
    worse["serve"]["deadline_miss_frac"] = 0.20
    d = diff_reports(base, worse, threshold_pct=5.0)
    assert "serve_deadline_miss_frac" in d["regressions"]
    assert d["metrics"]["serve_deadline_miss_frac"][
        "worse_direction"] == "up"
    # fewer misses never flag; nor does a sub-threshold drift
    assert "serve_deadline_miss_frac" not in diff_reports(
        worse, base, 5.0)["regressions"]
    slight = copy.deepcopy(base)
    slight["serve"]["deadline_miss_frac"] = 0.051   # +2%
    assert "serve_deadline_miss_frac" not in diff_reports(
        base, slight, 5.0)["regressions"]
    # zero baseline (every deadline met): misses appearing must still
    # flag though the percentage is undefined
    zero = copy.deepcopy(base)
    zero["serve"]["deadline_miss_frac"] = 0.0
    worse0 = copy.deepcopy(zero)
    worse0["serve"]["deadline_miss_frac"] = 0.10
    d0 = diff_reports(zero, worse0, threshold_pct=5.0)
    assert "serve_deadline_miss_frac" in d0["regressions"]
    assert d0["metrics"]["serve_deadline_miss_frac"]["pct"] is None
    # poison rows: mistyped or missing (== a fifo run, where the
    # rider is absent by contract) -> skipped, never a crash
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["deadline_miss_frac"] = "often"
    missing = copy.deepcopy(base)
    del missing["serve"]["deadline_miss_frac"]
    for a, b in ((base, poisoned), (poisoned, base),
                 (base, missing), (missing, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_deadline_miss_frac" in d["skipped"]
        assert "serve_deadline_miss_frac" not in d["regressions"]


def test_diff_poisoned_lifecycle_metrics_skip_not_crash(three_hosts):
    """Poisoned inputs for the new metrics: a mistyped (string/bool)
    or missing value must land the metric in `skipped`, never crash
    the diff or fabricate a regression."""
    import copy

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    poisoned = copy.deepcopy(base)
    poisoned["serve"]["queue_wait_p99_s"] = "slow"
    del poisoned["serve"]["preempted_time_frac"]
    for a, b in ((base, poisoned), (poisoned, base)):
        d = diff_reports(a, b, threshold_pct=5.0)
        assert "serve_queue_wait_p99_s" in d["skipped"]
        assert "serve_preempted_time_frac" in d["skipped"]
        assert not {"serve_queue_wait_p99_s",
                    "serve_preempted_time_frac"} & set(d["regressions"])


def test_diff_skips_metrics_missing_on_either_side(three_hosts):
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        diff_reports,
    )

    base = build_report(three_hosts)
    # the fixture's serve report has no decode_tokens_per_sec: skipped,
    # not silently dropped
    d = diff_reports(base, base, 5.0)
    assert "serve_decode_tokens_per_sec" in d["skipped"]
    withit = _perturbed(base, decode_tps=100.0)
    d2 = diff_reports(withit, _perturbed(base, decode_tps=50.0), 5.0)
    assert "serve_decode_tokens_per_sec" in d2["regressions"]


def test_cli_diff_exit_codes_and_text(three_hosts, tmp_path):
    """The one-command triage contract: 0 clean, 2 past threshold,
    1 unreadable input; --text renders the regression."""
    base = build_report(three_hosts)
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(_perturbed(base, step_p50=0.30)))

    def run(*argv):
        return subprocess.run(
            [sys.executable, _OBSCTL, "diff", *argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_REPO)

    clean = run(str(a), str(a))
    assert clean.returncode == 0, clean.stderr
    doc = json.loads(clean.stdout)
    assert doc["regressions"] == []

    bad = run(str(a), str(b), "--text")
    assert bad.returncode == 2
    assert "REGRESSED" in bad.stdout and "step_time_p50_s" in bad.stderr

    # raising the threshold past the delta silences the gate
    assert run(str(a), str(b), "--threshold-pct", "500").returncode == 0

    missing = run(str(a), str(tmp_path / "nope.json"))
    assert missing.returncode == 1

    invalid = tmp_path / "invalid.json"
    invalid.write_text(json.dumps({"not": "a report"}))
    assert run(str(a), str(invalid)).returncode == 1


# -- open-loop goodput replay (ISSUE 16: `obsctl goodput`) -------------------

def _open_loop_run(pid, rate, n, missed=(), t0=1000.0):
    """One open-loop run's serve events: the driver's stamp, then a
    verdict-carrying finish (+ queue-dominant timeline for misses) per
    request — the recorded shape `obsctl goodput` replays."""
    events = [_ev(0, t0, "serve", event="open_loop", process="poisson",
                  clock="wall", rate=float(rate), requests=n,
                  slo_ttft_s=0.1)]
    for e in events:
        e["pid"] = pid
    for rid in range(n):
        met = rid not in missed
        fin = _ev(0, t0 + 1 + rid, "serve", event="finish", request=rid,
                  tokens=8, preemptions=0, slo_met=met,
                  ttft_slo_met=met, slack_s=0.05 if met else -0.04)
        fin["pid"] = pid
        events.append(fin)
        if not met:
            tl = _ev(0, t0 + 1 + rid, "serve", event="request_timeline",
                     request=rid, at="finish", group="interactive",
                     e2e_s=1.0, queue_s=0.7, prefill_s=0.1,
                     decode_s=0.15, preempted_s=0.0, overhead_s=0.05,
                     tokens=8, prompt_len=4, preemptions=0,
                     segments=[])
            tl["pid"] = pid
            events.append(tl)
    return events


def test_cli_goodput_deterministic_sweep_and_knee(tmp_path):
    """The capacity answer end to end: two runs at different offered
    rates (underload clean, overload queue-bound) merge into one sweep
    with the knee at the overloaded rate — and the JSON is
    byte-identical across every input-path ordering."""
    lo = tmp_path / "lo"
    hi = tmp_path / "hi"
    _write(str(lo / "events.jsonl"), _open_loop_run(1, 8.0, 4))
    _write(str(hi / "events.jsonl"),
           _open_loop_run(2, 64.0, 4, missed=(1, 3)))

    def run(*argv):
        return subprocess.run(
            [sys.executable, _OBSCTL, "goodput", *argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_REPO)

    fwd = run(str(lo), str(hi))
    rev = run(str(hi), str(lo))
    assert fwd.returncode == 0, fwd.stderr
    assert fwd.stdout == rev.stdout          # byte-deterministic
    doc = json.loads(fwd.stdout)
    assert doc["runs"] == 2
    assert doc["overall_attainment"] == pytest.approx(0.75)
    assert [r["rate"] for r in doc["rates"]] == [8.0, 64.0]
    assert doc["rates"][0]["slo_attainment"] == 1.0
    assert doc["rates"][1]["slo_attainment"] == 0.5
    assert doc["rates"][1]["miss_phases"] == {"queue": 2}
    assert doc["knee"] == {"rate": 64.0, "target": 0.99}
    # per-run records carry the tenant split and the miss attribution
    runs = [r for p in doc["processes"] for r in p["runs"]]
    over = next(r for r in runs if r["rate"] == 64.0)
    assert over["dominant_miss_phase"] == "queue"
    assert over["group_slo_attainment"] == {"interactive": 0.0,
                                            "": 1.0}
    assert over["goodput_tokens"] == 16      # missed tokens don't count
    # --text names the knee
    text = run(str(lo), str(hi), "--text")
    assert text.returncode == 0
    assert "capacity knee at 64.0/s" in text.stdout
    # a higher knee target moves the knee down to the first rate that
    # fails it; an un-failed sweep reports no knee
    strict = json.loads(run(str(lo), "--knee-target", "0.5").stdout)
    assert strict["knee"] is None


def test_cli_goodput_min_attainment_exit_codes(tmp_path):
    """diff-style gating: rc 2 when overall attainment sits below the
    floor, rc 0 at or above it, rc 1 for nonsense flag values."""
    d = tmp_path / "run"
    _write(str(d / "events.jsonl"),
           _open_loop_run(1, 64.0, 4, missed=(1, 3)))   # attainment 0.5

    def run(*argv):
        return subprocess.run(
            [sys.executable, _OBSCTL, "goodput", str(d), *argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=_REPO)

    assert run().returncode == 0                      # no floor, no gate
    gated = run("--min-attainment", "0.99")
    assert gated.returncode == 2
    assert "below the --min-attainment floor" in gated.stderr
    assert run("--min-attainment", "0.5").returncode == 0
    assert run("--min-attainment", "1.5").returncode == 1
    assert run("--knee-target", "0").returncode == 1


def test_cli_goodput_rejects_closed_loop_and_malformed(tmp_path):
    """Strict-input contract: a closed-loop trace (no open_loop
    stamps) and a malformed stream both refuse with rc 1 — never a
    fabricated zero-attainment report."""
    closed = tmp_path / "closed"
    _write(str(closed / "events.jsonl"),
           [_ev(0, 1000.0, "serve", event="finish", request=0,
                tokens=4, preemptions=0)])
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "goodput", str(closed)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    assert proc.returncode == 1
    assert "no open_loop events" in proc.stderr
    # poison line mid-stream (a malformed FINAL line is a torn tail
    # from a mid-write kill and is tolerated by design)
    bad = tmp_path / "bad"
    run_events = _open_loop_run(1, 8.0, 2)
    _write(str(bad / "events.jsonl"), run_events[:-1])
    with open(str(bad / "events.jsonl"), "a", encoding="utf-8") as f:
        f.write("not json\n")
        f.write(json.dumps(run_events[-1]) + "\n")
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "goodput", str(bad)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    assert proc.returncode == 1
    assert "unparseable" in proc.stderr


def test_cli_diff_runs_without_jax():
    """diff stays on the stdlib-only side of the obs contract —
    statically (graftlint R1): obs/report.py (where diff lives) is in
    the jax-free zone's import closure and the zone holds."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
        PACKAGE,
        load_project,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.rules import (
        check_r1,
        r1_reachability,
    )

    project = load_project(_REPO)
    assert check_r1(project) == []
    assert f"{PACKAGE}/obs/report.py" in r1_reachability(project)


def test_cli_subprocess_smoke_without_jax(three_hosts, tmp_path):
    """Slow-tier RUNTIME backstop for the static R1 gate: R1 only
    proves import-time cleanliness (lazy function-body imports are
    sanctioned), so one poisoned subprocess still executes EVERY
    obsctl subcommand end-to-end — catching a jax dependency smuggled
    into a lazily-imported runtime path (timeline/slo/tail lazy-load
    obs.timeline inside their cmd_ functions, exactly the shape R1
    cannot see)."""
    base = build_report(three_hosts)
    a = tmp_path / "a.json"
    a.write_text(json.dumps(base))
    tail_file = tmp_path / "tail.jsonl"
    tail_file.write_text(json.dumps(
        {"v": 1, "t": 1000.0, "host": 0, "pid": 1, "type": "serve",
         "event": "iteration_ledger", "iteration": 0, "dur_s": 0.05,
         "prefill_s": 0.01, "decode_s": 0.03, "gather_bucket": 64,
         "prefill_chunks": 1, "prefill_dispatches": 1,
         "decode_slots": 3, "tokens": 4, "waiting": 2,
         "kv_used_frac": 0.5}) + "\n")
    # (argv, expected rc, expected output marker): timeline/slo run
    # their full load/validate path and exit 1 on the fixture's
    # timeline-less stream — asserting the MESSAGE distinguishes that
    # clean refusal from a jax-import crash
    cases = [
        (["report", *three_hosts], 0, None),
        (["diff", str(a), str(a)], 0, None),
        (["lint"], 0, None),
        (["timeline", *three_hosts], 1, "no request_timeline events"),
        (["slo", *three_hosts], 1, "no request_timeline events"),
        (["tail", str(tail_file), "--updates", "1",
          "--interval", "0.05"], 0, None),
        (["goodput", *three_hosts], 1, "no open_loop events"),
    ]
    for argv, want_rc, marker in cases:
        code = ("import sys, runpy; sys.modules['jax'] = None; "
                "sys.argv = ['obsctl'] + %r; "
                "runpy.run_path(%r, run_name='__main__')"
                % (list(argv), _OBSCTL))
        proc = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        assert proc.returncode == want_rc, (argv[0], proc.stdout)
        if marker is not None:
            assert marker in proc.stdout, (argv[0], proc.stdout)
