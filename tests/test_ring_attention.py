"""Ring attention (sequence parallelism) vs the XLA reference kernel.

Runs on the 8-fake-CPU-device mesh (conftest): the REAL shard_map /
ppermute code path, no TPU needed — the long-context capability the
reference lacks entirely (SURVEY.md §5.7: it truncates to 512).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    make_attention_mask,
    xla_attention,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    ring_attention,
    use_mesh,
)


def _qkv(b=4, h=2, s=32, d=8, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d), dtype)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh(devices8):
    # data=2 × seq=4: batch and sequence sharded simultaneously
    return build_mesh(MeshConfig(dp=2, sp=4), devices=devices8)


def test_ring_matches_xla_no_mask(sp_mesh):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=sp_mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_matches_xla_padding_mask(sp_mesh):
    q, k, v = _qkv()
    rng = np.random.RandomState(1)
    am = (rng.rand(4, 32) > 0.3).astype(np.int32)
    am[:, :4] = 1  # no fully-masked rows
    mask = make_attention_mask(jnp.asarray(am))
    ref = xla_attention(q, k, v, mask=mask)
    out = jax.jit(
        lambda q, k, v, m: ring_attention(q, k, v, mask=m, mesh=sp_mesh)
    )(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_causal(sp_mesh):
    q, k, v = _qkv(seed=2)
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        make_causal_mask,
    )
    ref = xla_attention(q, k, v, mask=make_causal_mask(32))
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh=sp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_gradients_match(sp_mesh):
    q, k, v = _qkv(seed=3)
    am = np.ones((4, 32), np.int32)
    am[:, 28:] = 0
    mask = make_attention_mask(jnp.asarray(am))

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, mask=mask) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mask=mask, mesh=sp_mesh) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)


def test_ring_bf16_close_to_fp32_reference(sp_mesh):
    q, k, v = _qkv(seed=4)
    ref = xla_attention(q, k, v)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=sp_mesh))(qb, kb, vb)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2)


def test_ring_rejects_indivisible_seq(sp_mesh):
    q, k, v = _qkv(s=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=sp_mesh)


def test_bert_train_step_with_ring_attention(devices8):
    """End-to-end: BERT forward+backward+update on a dp×sp mesh with
    attention_impl='ring' matches the same step with impl='xla'."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForSequenceClassification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    seq_len = 32
    losses = {}
    for impl, mesh_cfg in (("xla", MeshConfig(dp=-1)),
                           ("ring", MeshConfig(dp=2, sp=4))):
        mesh = build_mesh(mesh_cfg, devices=devices8)
        cfg = EncoderConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_heads=4, intermediate_size=64,
                            max_position_embeddings=seq_len,
                            hidden_dropout=0.0, attention_dropout=0.0,
                            attention_impl=impl)
        model = BertForSequenceClassification(cfg, num_labels=2)
        params = init_params(model, cfg, seed=0)
        tcfg = TrainConfig(dtype="float32", train_batch_size=1,
                           max_seq_length=seq_len, log_every_steps=0)
        trainer = Trainer(tcfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=128)
        texts, labels = synthetic_text_classification(16, seed=0)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq_len)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False, seed=0)
        batch = next(batcher.global_arrays(0))
        trainer.state, metrics = trainer._train_step(trainer.state, batch)
        losses[impl] = float(jax.device_get(metrics["loss"]))

    assert np.isfinite(losses["ring"])
    np.testing.assert_allclose(losses["ring"], losses["xla"], atol=1e-5)


def test_ring_long_context_seq2048_sp8(devices8):
    """Long-context evidence (SURVEY.md §5.7 beyond-parity): EXACT
    attention at seq 2048 with the sequence axis fully sharded over all
    8 devices (256 tokens per shard) — each device only ever holds
    O(seq/sp) keys/values at a time, the memory shape that makes
    sequences longer than one chip's HBM feasible."""
    mesh = build_mesh(MeshConfig(dp=1, sp=8), devices=devices8)
    q, k, v = _qkv(b=1, h=2, s=2048, d=8, seed=7)
    ref = xla_attention(q, k, v)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ring_long_context_causal_masked(devices8):
    """Same 2048/sp8 shape with causal + padding masks riding the ring."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        make_causal_mask,
    )

    mesh = build_mesh(MeshConfig(dp=1, sp=8), devices=devices8)
    q, k, v = _qkv(b=1, h=2, s=2048, d=8, seed=8)
    rng = np.random.RandomState(9)
    am = (rng.rand(1, 2048) > 0.2).astype(np.int32)
    am[:, :64] = 1
    pad = make_attention_mask(jnp.asarray(am))
    ref = xla_attention(q, k, v, mask=pad + make_causal_mask(2048, 2048))
    out = jax.jit(lambda q, k, v, m: ring_attention(
        q, k, v, mask=m, causal=True, mesh=mesh))(q, k, v, pad)
    # compare only valid query rows (fully-masked rows are don't-care)
    valid = am[0] > 0
    np.testing.assert_allclose(np.asarray(out)[0, :, valid],
                               np.asarray(ref)[0, :, valid], atol=1e-4)


def test_llama_train_step_with_ring_attention(devices8):
    """End-to-end: Llama causal-lm forward+backward+update on a dp×sp
    mesh with attention_impl='ring' matches the same step with
    impl='xla' — sequence parallelism on the modern decoder lineage
    (RoPE positions are global, so sharding the seq axis must not
    change the math)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    seq_len = 32
    losses = {}
    for impl, mesh_cfg in (("xla", MeshConfig(dp=-1)),
                           ("ring", MeshConfig(dp=2, sp=4))):
        mesh = build_mesh(mesh_cfg, devices=devices8)
        cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=64,
                          max_position_embeddings=seq_len,
                          attention_impl=impl)
        model = LlamaForCausalLM(cfg)
        params = init_params(model, cfg, seed=0)
        tcfg = TrainConfig(task="causal-lm", dtype="float32",
                           train_batch_size=1, max_seq_length=seq_len,
                           log_every_steps=0)
        trainer = Trainer(tcfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=128)
        texts, _ = synthetic_text_classification(16, seed=0)
        ds = ArrayDataset.from_lm_texts(tok, texts, max_length=seq_len)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False, seed=0)
        batch = next(batcher.global_arrays(0))
        trainer.state, metrics = trainer._train_step(trainer.state, batch)
        losses[impl] = float(jax.device_get(metrics["loss"]))

    assert np.isfinite(losses["ring"])
    np.testing.assert_allclose(losses["ring"], losses["xla"], atol=1e-5)
