"""Int8 weight-only quantization for generation (models/quant.py).

Beyond-parity: decode on TPU is HBM-bound, so int8 dense kernels
(dequantized into the matmul read, compute stays in the model dtype)
buy decode throughput. These tests pin the quantization math, the
Int8Dense layout, logits fidelity on a real HF checkpoint, decode
self-consistency through the KV cache, and the size accounting.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import transformers

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate_causal,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
    GPT2_QUANT_TARGETS,
    Int8Dense,
    quantize_for_generation,
    quantize_gpt2,
    quantize_kernel,
    quantize_params,
)


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=3, n_head=4,
        n_inner=64, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=2)
    d = str(tmp_path_factory.mktemp("gpt2q"))
    transformers.GPT2LMHeadModel(cfg).eval().save_pretrained(d)
    return d


def test_quantize_kernel_roundtrip_bound():
    rng = np.random.RandomState(0)
    w = (rng.randn(64, 48) * rng.uniform(0.01, 2.0, 48)[None, :]).astype(
        np.float32)
    q, scale = quantize_kernel(w)
    assert q.dtype == np.int8 and scale.shape == (48,)
    # symmetric rounding: error within half a scale step everywhere
    err = np.abs(w - q.astype(np.float32) * scale[None, :])
    assert np.all(err <= scale[None, :] / 2 + 1e-7)
    # a zero column must not produce NaN/inf scales
    w[:, 0] = 0.0
    q0, s0 = quantize_kernel(w)
    assert np.all(q0[:, 0] == 0) and np.isfinite(s0).all()


def test_int8_dense_matches_manual_dequant():
    rng = np.random.RandomState(1)
    w = rng.randn(16, 8).astype(np.float32)
    q, scale = quantize_kernel(w)
    bias = rng.randn(8).astype(np.float32)
    x = jnp.asarray(rng.randn(4, 16), jnp.float32)
    layer = Int8Dense(8, dtype=jnp.float32)
    params = {"kernel_q": jnp.asarray(q), "kernel_scale": jnp.asarray(scale),
              "bias": jnp.asarray(bias)}
    got = layer.apply({"params": params}, x)
    want = np.asarray(x) @ (q.astype(np.float32) * scale[None, :]) + bias
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


@pytest.mark.slow
def test_quantized_gpt2_logits_close(gpt2_dir):
    """Per-channel int8 on a real HF checkpoint: logits stay highly
    correlated with full precision (the quality contract for weight-only
    quantization)."""
    model, params, _, _ = auto_models.from_pretrained(gpt2_dir,
                                                      task="causal-lm")
    qmodel, qparams, stats = quantize_gpt2(model, params)
    assert stats["kernels_quantized"] == 3 * 4   # 3 layers x 4 denses
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, 128, (2, 12)))
    fp = np.asarray(model.apply({"params": params}, ids,
                                deterministic=True), np.float64)
    q8 = np.asarray(qmodel.apply({"params": qparams}, ids,
                                 deterministic=True), np.float64)
    corr = np.corrcoef(fp.ravel(), q8.ravel())[0, 1]
    assert corr > 0.999, corr
    rel = np.abs(q8 - fp).max() / (np.abs(fp).max() + 1e-9)
    assert rel < 0.05, rel


@pytest.mark.slow
def test_quantized_decode_self_consistent(gpt2_dir):
    """Quantized greedy generation through the KV cache must equal the
    argmax continuation of quantized full forward passes — cache decode
    correctness is independent of quantization error."""
    model, params, _, _ = auto_models.from_pretrained(gpt2_dir,
                                                      task="causal-lm")
    qmodel, qparams, _ = quantize_gpt2(model, params)
    rng = np.random.RandomState(2)
    ids = rng.randint(3, 128, (2, 6))
    new = 5
    got = np.asarray(generate_causal(qmodel, qparams, ids,
                                     max_new_tokens=new))
    cur = ids.copy()
    for _ in range(new):
        logits = qmodel.apply({"params": qparams}, jnp.asarray(cur),
                              deterministic=True)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    want = cur[:, ids.shape[1]:]
    # pad-after-EOS semantics: compare only up to each row's first EOS
    for b in range(ids.shape[0]):
        row_want = want[b]
        eos = np.where(row_want == 2)[0]
        upto = (eos[0] + 1) if len(eos) else new
        np.testing.assert_array_equal(got[b, :upto], row_want[:upto])


@pytest.mark.slow
def test_quantize_stats_bytes(gpt2_dir):
    """fp32 checkpoint → ~4x smaller dense kernels (int8 + a scale row)."""
    _, params, _, _ = auto_models.from_pretrained(gpt2_dir,
                                                  task="causal-lm")
    _, stats = quantize_params(params, GPT2_QUANT_TARGETS)
    ratio = stats["bytes_before"] / stats["bytes_after"]
    assert 3.5 < ratio <= 4.0, ratio


def test_quantize_rejects_non_gpt2():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForSequenceClassification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
        EncoderConfig,
    )

    cfg = EncoderConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=16)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg, seed=0)
    with pytest.raises(ValueError, match="GPT-2"):
        quantize_gpt2(model, params)


@pytest.mark.slow
def test_quantized_t5_and_bart_generate(tmp_path_factory):
    """The encoder-decoder families quantize and decode too: logits stay
    close to full precision and cached greedy generation runs."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate,
    )

    torch.manual_seed(0)
    cases = []
    t5_cfg = transformers.T5Config(
        vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, dropout_rate=0.0,
        decoder_start_token_id=0)
    d = str(tmp_path_factory.mktemp("t5q"))
    transformers.T5ForConditionalGeneration(t5_cfg).eval().save_pretrained(d)
    cases.append((d, 2 * (4 + 2) + 2 * (8 + 2)))  # enc: 2L x (attn4 + ffn2); dec adds cross-attn
    bart_cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        dropout=0.0, pad_token_id=1, bos_token_id=0, eos_token_id=2,
        decoder_start_token_id=2)
    d = str(tmp_path_factory.mktemp("bartq"))
    transformers.BartForConditionalGeneration(bart_cfg).eval().save_pretrained(d)
    cases.append((d, 2 * (4 + 2) + 2 * (8 + 2)))

    for model_dir, expect_kernels in cases:
        model, params, fam, _ = auto_models.from_pretrained(model_dir,
                                                            task="seq2seq")
        qmodel, qparams, stats = quantize_for_generation(model, params)
        assert stats["kernels_quantized"] == expect_kernels, (
            fam, stats["kernels_quantized"])
        rng = np.random.RandomState(0)
        src = jnp.asarray(rng.randint(3, 128, (2, 10)))
        dec_in = jnp.asarray(rng.randint(3, 128, (2, 6)))
        fp = np.asarray(model.apply({"params": params}, src, None, dec_in,
                                    deterministic=True), np.float64)
        q8 = np.asarray(qmodel.apply({"params": qparams}, src, None, dec_in,
                                     deterministic=True), np.float64)
        corr = np.corrcoef(fp.ravel(), q8.ravel())[0, 1]
        assert corr > 0.999, (fam, corr)
        out = np.asarray(generate(qmodel, qparams, src, max_new_tokens=4))
        assert out.shape == (2, 4)
