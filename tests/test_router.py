"""Multi-replica serving router (ISSUE 14): placement must change
WHERE a request runs, never WHAT it emits — per-request output is
token-identical to a single-engine run under every policy, across a
forced mid-trace drain, and under the randomized submit/drain/restart
conservation schedule (every submitted request finishes exactly once,
block pools restored free on every replica). The ``replicas=1`` router
is allowlist-gated byte-identical to the pre-router engine stream.
"""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
    Router,
    parse_placement,
    parse_replicas,
)


@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


_KW = dict(num_slots=2, block_size=4, num_blocks=40, prefill_chunk=8,
           max_model_len=64)


def _trace(seed=0, n=6):
    rng = np.random.RandomState(seed)
    lens = [(5, 7), (9, 3), (12, 10), (5, 4), (9, 8), (7, 6),
            (11, 5), (6, 9)][:n]
    return [(rng.randint(1, 120, (p,)).astype(np.int32), m)
            for p, m in lens]


def _single_outputs(model, params, trace, **kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    eng = ServeEngine(model, params, **kw)
    reqs = [eng.submit(p, m) for p, m in trace]
    eng.run()
    return [list(eng.output_ids(r)) for r in reqs]


@pytest.mark.parametrize("placement",
                         ["round_robin", "least_loaded", "affinity"])
def test_router_output_token_identical_to_single_engine(gpt2_setup,
                                                        placement):
    """The ISSUE 14 core contract: 2-replica output per request equals
    the single-engine run's under every placement policy (the engine's
    per-request exactness is placement-blind), and both replicas
    actually served traffic."""
    _cfg, model, params = gpt2_setup
    trace = _trace()
    base = _single_outputs(model, params, trace, **_KW)
    router = Router(model, params, replicas=2, placement=placement,
                    **_KW)
    reqs = [router.submit(p, m) for p, m in trace]
    router.run()
    assert [list(router.output_ids(q)) for q in reqs] == base
    owners = {router.replica_of(q) for q in reqs}
    assert owners == {0, 1}
    slo = router.slo_summary()
    assert slo["replicas"] == 2 and slo["placement"] == placement
    assert slo["requests"] == len(trace)
    assert slo["replica_load_imbalance"] >= 1.0


def test_router_sampled_streams_bitwise_identical_across_placement(
        gpt2_setup):
    """Sampled requests are seeded per request, so placement cannot
    change the stream: bitwise-identical outputs single vs routed."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(3)
    trace = [(rng.randint(1, 120, (7,)).astype(np.int32), 6, 11 + i)
             for i in range(4)]
    eng = ServeEngine(model, params, **_KW)
    ereqs = [eng.submit(p, m, temperature=0.9, top_k=20, seed=s)
             for p, m, s in trace]
    eng.run()
    base = [list(eng.output_ids(r)) for r in ereqs]
    router = Router(model, params, replicas=2,
                    placement="least_loaded", **_KW)
    rreqs = [router.submit(p, m, temperature=0.9, top_k=20, seed=s)
             for p, m, s in trace]
    router.run()
    assert [list(router.output_ids(q)) for q in rreqs] == base


def test_router_drain_mid_trace_token_identical_and_conserving(
        gpt2_setup):
    """The drain acceptance gate: a forced mid-trace drain finishes
    EVERY request with outputs token-identical to an undrained run —
    waiting requests requeue to the sibling (recompute semantics),
    resident ones finish in place — and both replicas' block pools
    come back fully free."""
    _cfg, model, params = gpt2_setup
    trace = _trace(n=8)
    kw = dict(num_slots=2, block_size=4, num_blocks=14, prefill_chunk=8,
              max_model_len=64)
    base = _single_outputs(model, params, trace, **kw)

    router = Router(model, params, replicas=2, placement="round_robin",
                    **kw)
    reqs = [router.submit(p, m) for p, m in trace]
    router.warmup()
    for _ in range(2):
        router.step()
    moved = router.drain(0)
    assert moved, "drain must have found waiting requests to requeue"
    assert router.requeues == len(moved)
    assert all(router.replica_of(q) == 1 for q in moved)
    # draining the last admitting replica is an outage, not a drain
    with pytest.raises(ValueError):
        router.drain(1)
    router.run()
    assert [list(router.output_ids(q)) for q in reqs] == base
    assert len(router.finished) == len(trace)
    for eng in router.engines:
        assert eng.blocks.num_used == 0
        assert (eng.blocks.num_free + eng.blocks.num_cached
                == eng.blocks.num_blocks - 1)
    # restart re-admits: new traffic may land on replica 0 again
    router.restart(0)
    extra = [router.submit(p, m) for p, m in _trace(seed=9, n=4)]
    router.run()
    assert {router.replica_of(q) for q in extra} == {0, 1}


def test_router_conservation_under_random_drain_restart_schedule(
        gpt2_setup):
    """The ISSUE 14 conservation property: a randomized submit / step /
    drain / restart schedule across 3 replicas loses and duplicates
    NOTHING — every submitted request finishes exactly once somewhere,
    and every replica's block pool is restored free."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(7)
    kw = dict(num_slots=2, block_size=4, num_blocks=14, prefill_chunk=8,
              max_model_len=64)
    router = Router(model, params, replicas=3, placement="least_loaded",
                    **kw)
    router.warmup()
    submitted = []
    for step_i in range(30):
        op = rng.rand()
        if op < 0.5 and len(submitted) < 16:
            p = rng.randint(1, 120, (int(rng.randint(4, 12)),))
            submitted.append(
                router.submit(p.astype(np.int32), int(rng.randint(2, 9))))
        elif op < 0.65:
            admitting = [i for i in range(3) if i not in router._draining]
            if len(admitting) > 1:
                router.drain(int(rng.choice(admitting)))
        elif op < 0.8 and router._draining:
            router.restart(int(rng.choice(sorted(router._draining))))
        if router.has_work():
            router.step()
    router.run()
    finished_sets = [set(e.finished) for e in router.engines]
    # exactly once: the per-replica finished sets are disjoint and
    # their union is exactly the submitted rid set
    assert sum(len(s) for s in finished_sets) == len(submitted)
    union = set().union(*finished_sets)
    assert union == {q.rid for q in submitted}
    assert all(len(router.output_ids(q)) > 0 for q in submitted)
    assert router.drains > 0
    for eng in router.engines:
        assert eng.blocks.num_used == 0
        assert (eng.blocks.num_free + eng.blocks.num_cached
                == eng.blocks.num_blocks - 1)


def test_router_affinity_keeps_families_sticky_and_aged(gpt2_setup):
    """Affinity placement: requests sharing a templated prefix land on
    one replica (the router-level fingerprint index, built from the
    same chain-key hashing as the BlockManager's prefix index), and
    the index ages — a tiny cap still serves exactly, it just forgets
    old families."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(5)
    prefixes = [rng.randint(1, 120, (12,)).astype(np.int32)
                for _ in range(2)]
    trace = []
    for j in range(3):
        for f in range(2):
            tail = rng.randint(1, 120, (3,)).astype(np.int32)
            trace.append((np.concatenate([prefixes[f], tail]), 4))
    router = Router(model, params, replicas=2, placement="affinity",
                    **_KW)
    reqs = [router.submit(p, m) for p, m in trace]
    router.run()
    # family f = trace rows f, f+2, f+4: one replica each, distinct
    owners = [router.replica_of(q) for q in reqs]
    fam0, fam1 = owners[0::2], owners[1::2]
    assert len(set(fam0)) == 1 and len(set(fam1)) == 1
    assert set(fam0) != set(fam1)       # least-loaded seeded them apart
    assert router.affinity_fallbacks == 0
    # a capped index evicts oldest fingerprints but never affects
    # output correctness
    tiny = Router(model, params, replicas=2, placement="affinity",
                  affinity_cap=2, **_KW)
    treqs = [tiny.submit(p, m) for p, m in trace]
    tiny.run()
    assert len(tiny._affinity) <= 2
    assert ([list(tiny.output_ids(q)) for q in treqs]
            == [list(router.output_ids(q)) for q in reqs])


def test_router_affinity_imbalance_bound_falls_back_to_load(gpt2_setup):
    """Affinity never starves load balance: once the sticky replica is
    more than ``affinity_max_skew`` load units deeper than the
    lightest sibling, placement falls back to least-loaded."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(6)
    prefix = rng.randint(1, 120, (12,)).astype(np.int32)
    router = Router(model, params, replicas=2, placement="affinity",
                    affinity_max_skew=2, **_KW)
    reqs = []
    for _ in range(6):   # same family, no stepping: queue 0 deepens
        tail = rng.randint(1, 120, (3,)).astype(np.int32)
        reqs.append(router.submit(np.concatenate([prefix, tail]), 3))
    owners = [router.replica_of(q) for q in reqs]
    sticky = owners[0]
    assert owners[1] == sticky           # affinity held while light
    assert (1 - sticky) in owners        # ...then the bound kicked in
    assert router.affinity_fallbacks > 0
    router.run()
    assert len(router.finished) == len(reqs)


def test_router_single_replica_is_byte_identical_passthrough(
        gpt2_setup, tmp_path):
    """The ``--replicas 1`` contract, allowlist-gated like
    ``overlap=off``: a 1-replica router's telemetry stream carries the
    SAME event sequence with the SAME key sets as the bare engine —
    no router event subtypes, no replica/placement keys anywhere, and
    nothing new in the SLO summary."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    _cfg, model, params = gpt2_setup
    trace = _trace(n=5)

    def run_instrumented(build):
        out = tmp_path / f"t{build.__name__}"
        obs.reset(out_dir=str(out), enabled=True)
        try:
            srv = build()
            for p, m in trace:
                srv.submit(p, m)
            srv.run()
            obs.flush()
        finally:
            obs.reset()
        events = [e for _, e, err in obs.iter_events(
            str(out / "events.jsonl")) if err is None]
        return srv, [e for e in events if e["type"] == "serve"]

    def engine():
        return ServeEngine(model, params, **_KW)

    def router():
        return Router(model, params, replicas=1, **_KW)

    eng, eng_ev = run_instrumented(engine)
    rt, rt_ev = run_instrumented(router)
    # identical event sequence: same kinds, same key sets, in order
    assert ([(e["event"], tuple(sorted(e))) for e in rt_ev]
            == [(e["event"], tuple(sorted(e))) for e in eng_ev])
    router_keys = {"replica", "replicas", "placement", "requeued",
                   "to_replica", "drains", "requeues",
                   "replica_load_imbalance", "per_replica",
                   "affinity_fallbacks"}
    for e in rt_ev:
        leaked = router_keys & set(e)
        assert not leaked, (e["event"], leaked)
    assert not any(k in rt.slo_summary() for k in router_keys)
    assert rt.engines[0].replica is None


def test_router_two_replica_stream_is_tagged_and_schema_valid(
        gpt2_setup, tmp_path):
    """With N > 1 every per-request lifecycle event (and the
    request_timeline) carries the owning ``replica``, the router run
    ends with per-replica reports plus ONE aggregate report (last —
    the one ``obs/report.py`` keeps), and the produced stream passes
    the schema validator."""
    _cfg, model, params = gpt2_setup
    out = tmp_path / "t2"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        router = Router(model, params, replicas=2,
                        placement="round_robin", **_KW)
        reqs = [router.submit(p, m) for p, m in _trace(n=5)]
        router.run()
        obs.flush()
    finally:
        obs.reset()
    count, errors = obs.validate_events_file(str(out / "events.jsonl"))
    assert not errors and count > 0
    events = [e for _, e, err in obs.iter_events(
        str(out / "events.jsonl")) if err is None]
    serve = [e for e in events if e["type"] == "serve"]
    for kind in ("submit", "admit", "first_token", "finish",
                 "request_timeline"):
        rows = [e for e in serve if e.get("event") == kind]
        assert rows, kind
        assert all(isinstance(e.get("replica"), int) for e in rows), kind
    owners = {router.replica_of(q) for q in reqs}
    finishes = {e["replica"] for e in serve if e["event"] == "finish"}
    assert finishes == owners == {0, 1}
    reports = [e for e in serve if e.get("event") == "report"]
    assert len(reports) == 3             # 2 replica reports + aggregate
    assert [r.get("replica") for r in reports[:2]] == [0, 1]
    agg = reports[-1]
    assert agg["replicas"] == 2 and agg["placement"] == "round_robin"
    assert isinstance(agg["replica_load_imbalance"], float)
    assert isinstance(agg["per_replica"], list) and len(
        agg["per_replica"]) == 2
    # the merged cross-host report keeps the aggregate (last) view
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        build_report,
    )

    rep = build_report([str(out)])
    assert rep["serve"]["replicas"] == 2
    assert rep["serve"]["replica_load_imbalance"] \
        == agg["replica_load_imbalance"]


def test_router_rejected_submit_leaves_placement_state_untouched(
        gpt2_setup):
    """A submit the scheduler rejects (over-length) must not advance
    the round-robin rotation or pollute the affinity index — placement
    state commits only for ACCEPTED requests."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(4)
    too_long = rng.randint(1, 120, (60,)).astype(np.int32)  # +16 > 64
    ok = rng.randint(1, 120, (8,)).astype(np.int32)

    rr = Router(model, params, replicas=2, placement="round_robin",
                **_KW)
    with pytest.raises(ValueError):
        rr.submit(too_long, 16)
    assert rr._rr == 0
    first = rr.submit(ok, 3)
    assert rr.replica_of(first) == 0     # rotation starts unskewed

    aff = Router(model, params, replicas=2, placement="affinity", **_KW)
    with pytest.raises(ValueError):
        aff.submit(too_long, 16)
    assert not aff._affinity             # no fingerprints registered
    rr.run(), aff.run()


def test_router_knob_parsing(monkeypatch):
    assert parse_replicas(None) == 1
    assert parse_replicas("3") == 3
    monkeypatch.setenv("HSTD_SERVE_REPLICAS", "4")
    assert parse_replicas(None) == 4
    with pytest.raises(ValueError):
        parse_replicas("0")
    with pytest.raises(ValueError):
        parse_replicas("many")
    assert parse_placement(None) == "round_robin"
    assert parse_placement("AFFINITY") == "affinity"
    monkeypatch.setenv("HSTD_SERVE_PLACEMENT", "least_loaded")
    assert parse_placement(None) == "least_loaded"
    with pytest.raises(ValueError):
        parse_placement("random")


def test_router_affinity_speculative_prefix_composition(gpt2_setup):
    """The heaviest composition (slow tier, ISSUE 14 budget): affinity
    placement x speculative decode x prefix caching across 2 replicas
    stays token-identical to the same single speculative engine, with
    the per-replica prefix caches actually hitting."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(11)
    prefixes = [rng.randint(1, 120, (12,)).astype(np.int32)
                for _ in range(2)]
    trace = []
    for j in range(3):
        for f in range(2):
            tail = rng.randint(1, 120, (3,)).astype(np.int32)
            trace.append((np.concatenate([prefixes[f], tail]), 5))
    kw = dict(num_slots=2, block_size=4, num_blocks=60, prefill_chunk=8,
              max_model_len=64, speculate_k=2, draft=1,
              prefix_cache=True)
    eng = ServeEngine(model, params, **kw)
    ereqs = [eng.submit(p, m) for p, m in trace]
    eng.run()
    base = [list(eng.output_ids(r)) for r in ereqs]
    router = Router(model, params, replicas=2, placement="affinity",
                    **kw)
    rreqs = [router.submit(p, m) for p, m in trace]
    router.run()
    assert [list(router.output_ids(q)) for q in rreqs] == base
    slo = router.slo_summary()
    assert slo.get("cache_hit_rate", 0) > 0
    # sticky families: each family's requests share one replica
    owners = [router.replica_of(q) for q in rreqs]
    assert len(set(owners[0::2])) == 1 and len(set(owners[1::2])) == 1
