"""Llama-family decoder (models/llama.py): HF torch parity (RoPE, GQA,
SwiGLU, RMSNorm), cached decode, export roundtrip, training, and
composition with the framework machinery (fused CE, LoRA, int8)."""

import numpy as np
import pytest
import torch
import transformers
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate_causal,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)

TOL = 3e-4


@pytest.fixture(scope="module", params=["gqa", "mha"])
def llama_dir(request, tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2 if request.param == "gqa" else 4,
        intermediate_size=64, max_position_embeddings=64,
        rms_norm_eps=1e-5, bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False, attention_dropout=0.0)
    d = str(tmp_path_factory.mktemp(f"llama_{request.param}"))
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(d)
    return d


def _inputs(batch=3, seq=10, vocab=128, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(3, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    return ids, mask


def test_llama_lm_parity(llama_dir):
    model, params, family, cfg = auto_models.from_pretrained(
        llama_dir, task="causal-lm")
    assert family == "llama"
    m = transformers.LlamaForCausalLM.from_pretrained(llama_dir).eval()
    ids, mask = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids),
                  attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


@pytest.mark.slow
def test_llama_incremental_decode_matches_full(llama_dir):
    model, params, _, _ = auto_models.from_pretrained(llama_dir,
                                                      task="causal-lm")
    rng = np.random.RandomState(2)
    ids = rng.randint(3, 128, (2, 6))
    new = 5
    got = np.asarray(generate_causal(model, params, ids,
                                     max_new_tokens=new))
    cur = ids.copy()
    for _ in range(new):
        logits = model.apply({"params": params}, jnp.asarray(cur),
                             deterministic=True)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    want = cur[:, ids.shape[1]:]
    for b in range(ids.shape[0]):
        row = want[b]
        eos = np.where(row == 2)[0]
        upto = (eos[0] + 1) if len(eos) else new
        np.testing.assert_array_equal(got[b, :upto], row[:upto])


@pytest.mark.slow
def test_llama_export_roundtrip(llama_dir, tmp_path):
    model, params, family, cfg = auto_models.from_pretrained(
        llama_dir, task="causal-lm")
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, cfg)
    m1 = transformers.LlamaForCausalLM.from_pretrained(llama_dir).eval()
    m2 = transformers.LlamaForCausalLM.from_pretrained(out).eval()
    ids, _ = _inputs()
    with torch.no_grad():
        a = m1(input_ids=torch.tensor(ids)).logits.numpy()
        b = m2(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)


@pytest.mark.slow
def test_llama_trains_causal_lm(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
    cfg = LlamaConfig(vocab_size=256, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=32)
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg, seed=0)
    tcfg = TrainConfig(task="causal-lm", dtype="float32", learning_rate=3e-3,
                       scale_lr_by_world_size=False, log_every_steps=0,
                       rng_impl="threefry", epochs=2)
    trainer = Trainer(tcfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=32)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    assert hist["loss"][-1] < hist["loss"][0]


@pytest.mark.slow
def test_llama_fused_ce_matches_unfused(devices8):
    """hidden_and_embedding drives the fused vocab-CE (untied lm_head):
    fused and unfused first-step training losses must match."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        make_fused_causal_lm_loss,
    )

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(16, seed=2)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=16)

    def first_loss(fused):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        cfg = LlamaConfig(vocab_size=256, hidden_size=128, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=256,
                          max_position_embeddings=16)
        model = LlamaForCausalLM(cfg)
        params = init_params(model, cfg, seed=0)
        tcfg = TrainConfig(task="causal-lm", dtype="float32",
                           learning_rate=1e-3, scale_lr_by_world_size=False,
                           log_every_steps=0, rng_impl="threefry",
                           fused_vocab_ce=fused)
        trainer = Trainer(tcfg, model, params, mesh)
        if fused:
            trainer.loss_fn = make_fused_causal_lm_loss(model,
                                                        interpret=True)
        batch = next(ShardedBatcher(ds, 16, mesh, shuffle=False,
                                    seed=0).global_arrays(0))
        _, m = trainer._train_step(trainer.state, batch)
        return float(jax.device_get(m["loss"]))

    np.testing.assert_allclose(first_loss(True), first_loss(False),
                               rtol=2e-5)


@pytest.mark.slow
def test_llama_int8_and_lora_compose(llama_dir):
    """int8 weight-only decode quantizes exactly the seven projections
    per layer; LoRA's attention preset matches the q/k/v/o kernels."""
    from flax.traverse_util import flatten_dict

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
        init_lora_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
        quantize_for_generation,
    )

    model, params, _, _ = auto_models.from_pretrained(llama_dir,
                                                      task="causal-lm")
    qmodel, qparams, stats = quantize_for_generation(model, params)
    assert stats["kernels_quantized"] == 3 * 7     # 3 layers x 7 projs
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, 128, (2, 12)))
    fp = np.asarray(model.apply({"params": params}, ids,
                                deterministic=True), np.float64)
    q8 = np.asarray(qmodel.apply({"params": qparams}, ids,
                                 deterministic=True), np.float64)
    assert np.corrcoef(fp.ravel(), q8.ravel())[0, 1] > 0.999
    out = np.asarray(generate_causal(qmodel, qparams, ids[:, :6],
                                     max_new_tokens=4))
    assert out.shape == (2, 4)

    lora = init_lora_params(params, rank=4, targets="attention")
    paths = {"/".join(p[:-1]) for p in flatten_dict(lora)}
    assert len(paths) == 3 * 4                     # q/k/v/o per layer
    assert all(p.endswith(("q_proj/kernel", "k_proj/kernel",
                           "v_proj/kernel", "o_proj/kernel"))
               for p in paths)


@pytest.mark.slow
def test_llama_generate_left_padded(llama_dir):
    """A left-padded prompt generates the same continuation as the same
    prompt without padding (generate_causal supplies mask-derived
    positions; pads fully masked from the cache)."""
    model, params, _, _ = auto_models.from_pretrained(llama_dir,
                                                      task="causal-lm")
    prompt = np.asarray([[5, 9, 17, 33]])
    padded = np.asarray([[0, 0, 5, 9, 17, 33]])
    pmask = np.asarray([[0, 0, 1, 1, 1, 1]])
    a = np.asarray(generate_causal(model, params, prompt, max_new_tokens=4))
    b = np.asarray(generate_causal(model, params, padded, pmask,
                                   max_new_tokens=4))
    np.testing.assert_array_equal(a, b)


def test_llama_rejects_unsupported_layouts():
    """rope_scaling (3.1+ frequency scaling) and biased projections must
    raise at load instead of silently diverging from HF."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        llama_config_from_hf,
    )

    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=1,
                num_attention_heads=4, intermediate_size=64)
    with pytest.raises(ValueError, match="rope_scaling"):
        llama_config_from_hf({**base, "rope_scaling":
                              {"rope_type": "llama3", "factor": 8.0}})
    # trivial/default scaling passes
    llama_config_from_hf({**base, "rope_scaling": None})
    llama_config_from_hf({**base,
                          "rope_scaling": {"rope_type": "default"}})
    with pytest.raises(ValueError, match="attention_bias"):
        llama_config_from_hf({**base, "attention_bias": True})


@pytest.mark.slow
def test_llama_trains_on_tp_mesh(devices8):
    """dp2 x tp2 x fsdp2 reproduces the plain-dp loss sequence: the
    Megatron rules cover the *_proj kernels (q/k/v/gate/up column-,
    o/down row-parallel) and GQA survives head sharding at kv_heads=2
    over tp=2."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    def losses(mesh_cfg):
        mesh = build_mesh(mesh_cfg, devices=devices8)
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=128,
                          max_position_embeddings=32)
        model = LlamaForCausalLM(cfg)
        params = init_params(model, cfg, seed=0)
        tcfg = TrainConfig(task="causal-lm", dtype="float32",
                           learning_rate=1e-3, scale_lr_by_world_size=False,
                           log_every_steps=0, rng_impl="threefry", epochs=2)
        trainer = Trainer(tcfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, _ = synthetic_text_classification(32, seed=0)
        ds = ArrayDataset.from_lm_texts(tok, texts, max_length=32)
        return trainer.fit(ShardedBatcher(ds, 8, mesh, shuffle=False,
                                          seed=0))["loss"]

    np.testing.assert_allclose(losses(MeshConfig(dp=2, tp=2, fsdp=2)),
                               losses(MeshConfig(dp=-1)), rtol=2e-5)


@pytest.mark.slow
def test_mistral_parity_with_binding_window(tmp_path):
    """Mistral = Llama layout + sliding-window attention. With window <
    seq the band actually binds, so this checks the banding math against
    HF MistralForCausalLM, not just the shared layout."""
    torch.manual_seed(0)
    cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        sliding_window=4, attention_dropout=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=0)
    d = str(tmp_path / "mistral")
    hf = transformers.MistralForCausalLM(cfg).eval()
    hf.save_pretrained(d)
    model, params, family, mcfg = auto_models.from_pretrained(
        d, task="causal-lm")
    assert family == "llama" and mcfg.sliding_window == 4
    ids, mask = _inputs(seq=12)
    with torch.no_grad():
        t_out = hf(input_ids=torch.tensor(ids),
                   attention_mask=torch.tensor(mask),
                   use_cache=False)
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
    # windowed cached decode stays self-consistent
    got = np.asarray(generate_causal(model, params, ids[:1, :6],
                                     max_new_tokens=4))
    cur = ids[:1, :6].copy()
    for _ in range(4):
        lg = model.apply({"params": params}, jnp.asarray(cur),
                         deterministic=True)
        cur = np.concatenate(
            [cur, np.asarray(jnp.argmax(lg[:, -1], -1))[:, None]], axis=1)
    row = cur[0, 6:]
    eos = np.where(row == 2)[0]
    upto = (eos[0] + 1) if len(eos) else 4
    np.testing.assert_array_equal(got[0, :upto], row[:upto])
    # export round-trips as model_type mistral
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, mcfg)
    m2 = transformers.MistralForCausalLM.from_pretrained(out).eval()
    with torch.no_grad():
        a = hf(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
        b = m2(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)


@pytest.mark.slow
def test_qwen2_parity_with_qkv_biases(tmp_path):
    """Qwen2 = Llama layout + hardcoded q/k/v biases. Parity proves the
    biases load and apply (dropping them would shift every logit)."""
    torch.manual_seed(0)
    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        attention_dropout=0.0, use_sliding_window=False,
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False)
    d = str(tmp_path / "qwen2")
    hf = transformers.Qwen2ForCausalLM(cfg).eval()
    # HF _init_weights zeroes fresh Linear biases; randomize them so
    # bias loading is load-bearing in the parity comparison
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.05)
    hf.save_pretrained(d)
    model, params, family, mcfg = auto_models.from_pretrained(
        d, task="causal-lm")
    assert family == "llama" and mcfg.qkv_bias
    # the biases really landed (nonzero after torch init)
    b = params["backbone"]["layers_0"]["self_attn"]["q_proj"]["bias"]
    assert float(np.abs(np.asarray(b)).max()) > 0
    ids, mask = _inputs(seq=10)
    with torch.no_grad():
        t_out = hf(input_ids=torch.tensor(ids),
                   attention_mask=torch.tensor(mask), use_cache=False)
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, mcfg)
    m2 = transformers.Qwen2ForCausalLM.from_pretrained(out).eval()
    with torch.no_grad():
        a = hf(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
        bb = m2(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
    np.testing.assert_allclose(bb, a, atol=1e-5)


@pytest.mark.slow
def test_mistral_windowed_decode_right_padded(tmp_path):
    """The sliding window must count LOGICAL positions, not KV-buffer
    slots: a right-padded prompt generates the same continuation as the
    unpadded prompt (buffer-slot windowing would exclude valid keys)."""
    torch.manual_seed(0)
    cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        sliding_window=4, attention_dropout=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=0)
    d = str(tmp_path / "mistral")
    transformers.MistralForCausalLM(cfg).eval().save_pretrained(d)
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    prompt = np.asarray([[5, 6, 7, 8, 9, 10]])
    padded = np.concatenate([prompt, np.zeros((1, 2), prompt.dtype)], 1)
    pmask = np.asarray([[1, 1, 1, 1, 1, 1, 0, 0]])
    a = np.asarray(generate_causal(model, params, prompt, max_new_tokens=3))
    b = np.asarray(generate_causal(model, params, padded, pmask,
                                   max_new_tokens=3))
    np.testing.assert_array_equal(a, b)
    # left-padded too
    lpad = np.concatenate([np.zeros((1, 2), prompt.dtype), prompt], 1)
    lmask = np.asarray([[0, 0, 1, 1, 1, 1, 1, 1]])
    c = np.asarray(generate_causal(model, params, lpad, lmask,
                                   max_new_tokens=3))
    np.testing.assert_array_equal(a, c)


@pytest.mark.slow
def test_qwen2_per_layer_window_parity(tmp_path):
    """use_sliding_window=True with max_window_layers: only layers >=
    the threshold slide (HF layer_types semantics) — parity against HF
    with a BINDING window on a mixed stack, plus config roundtrip."""
    torch.manual_seed(0)
    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        attention_dropout=0.0, use_sliding_window=True,
        sliding_window=4, max_window_layers=1,
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False)
    d = str(tmp_path / "qwen2w")
    hf = transformers.Qwen2ForCausalLM(cfg).eval()
    hf.save_pretrained(d)
    model, params, family, mcfg = auto_models.from_pretrained(
        d, task="causal-lm")
    assert mcfg.sliding_window == 4
    assert mcfg.sliding_window_start_layer == 1
    ids, mask = _inputs(seq=12)
    with torch.no_grad():
        t_out = hf(input_ids=torch.tensor(ids),
                   attention_mask=torch.tensor(mask), use_cache=False)
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
    # roundtrip: re-exported config keeps the per-layer policy
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, mcfg)
    import json

    with open(f"{out}/config.json") as f:
        exported = json.load(f)
    assert exported["use_sliding_window"] is True
    assert exported["max_window_layers"] == 1


@pytest.mark.slow
def test_gemma_parity(tmp_path):
    """Gemma-1 = Llama layout + sqrt(hidden) embedding scale + (1+w)
    fp32 RMSNorm + tanh-gelu MLP + an INDEPENDENT head_dim + tied head
    — parity against HF GemmaForCausalLM with head_dim != hidden/heads
    so every variant knob is load-bearing."""
    torch.manual_seed(0)
    cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,                       # != 48/4 = 12: independent
        intermediate_size=96, max_position_embeddings=64,
        hidden_activation="gelu_pytorch_tanh", attention_dropout=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=0)
    d = str(tmp_path / "gemma")
    hf = transformers.GemmaForCausalLM(cfg).eval()
    hf.save_pretrained(d)
    model, params, family, mcfg = auto_models.from_pretrained(
        d, task="causal-lm")
    assert family == "llama" and mcfg.model_type == "gemma"
    assert mcfg.head_dim == 16 and mcfg.embed_scale and mcfg.rms_unit_offset
    assert mcfg.tie_word_embeddings
    ids, mask = _inputs(seq=10)
    with torch.no_grad():
        t_out = hf(input_ids=torch.tensor(ids),
                   attention_mask=torch.tensor(mask), use_cache=False)
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
    # cached decode self-consistency with the independent head_dim
    got = np.asarray(generate_causal(model, params, ids[:1, :6],
                                     max_new_tokens=4))
    cur = ids[:1, :6].copy()
    for _ in range(4):
        lg = model.apply({"params": params}, jnp.asarray(cur),
                         deterministic=True)
        cur = np.concatenate(
            [cur, np.asarray(jnp.argmax(lg[:, -1], -1))[:, None]], axis=1)
    row = cur[0, 6:]
    eos = np.where(row == 2)[0]
    upto = (eos[0] + 1) if len(eos) else 4
    np.testing.assert_array_equal(got[0, :upto], row[:upto])
    # export round-trips as model_type gemma
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, mcfg)
    m2 = transformers.GemmaForCausalLM.from_pretrained(out).eval()
    with torch.no_grad():
        a = hf(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
        b = m2(input_ids=torch.tensor(ids), use_cache=False).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)


@pytest.mark.slow
def test_independent_head_dim_outside_gemma(tmp_path):
    """head_dim is honored generically (Mistral-Nemo-style configs
    serialize head_dim != hidden/heads under model_type mistral)."""
    torch.manual_seed(0)
    cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        intermediate_size=64, max_position_embeddings=64,
        sliding_window=None, attention_dropout=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=0)
    d = str(tmp_path / "nemo")
    hf = transformers.MistralForCausalLM(cfg).eval()
    hf.save_pretrained(d)
    model, params, _, mcfg = auto_models.from_pretrained(d,
                                                         task="causal-lm")
    assert mcfg.resolved_head_dim == 16
    ids, mask = _inputs(seq=10)
    with torch.no_grad():
        t_out = hf(input_ids=torch.tensor(ids),
                   attention_mask=torch.tensor(mask), use_cache=False)
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


def test_windowed_decode_requires_position_ids_with_mask():
    """decode + sliding_window + attention_mask without position_ids is
    a coordinate-system mix (logical keys vs buffer-slot queries) — the
    model refuses instead of silently mis-windowing padded prompts;
    generate_causal always supplies mask-derived positions."""
    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=1,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=32, sliding_window=4,
                      model_type="mistral")
    model = LlamaForCausalLM(cfg)
    params = auto_models.init_params(model, cfg)
    ids = jnp.ones((2, 8), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    with pytest.raises(ValueError, match="position_ids"):
        model.apply({"params": params}, ids, mask, decode=True,
                    mutable=["cache"])
    # unpadded decode (no mask) keeps working: slots == logical positions
    out, _ = model.apply({"params": params}, ids, decode=True,
                         mutable=["cache"])
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("scaling", [
    {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
     "high_freq_factor": 4.0, "original_max_position_embeddings": 16},
    {"rope_type": "linear", "factor": 4.0},
], ids=["llama3", "linear"])
def test_rope_scaling_parity(tmp_path, scaling):
    """Llama-3.1-style rope_scaling (NTK-by-parts) and linear position
    interpolation match HF logits — positions past the ORIGINAL context
    included, which is where the scaled frequencies actually differ."""
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        rope_scaling=dict(scaling), rms_norm_eps=1e-5,
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False, attention_dropout=0.0)
    d = str(tmp_path / "scaled")
    transformers.LlamaForCausalLM(cfg).eval().save_pretrained(d)

    model, params, _, mcfg = auto_models.from_pretrained(d, task="causal-lm")
    assert mcfg.rope_scaling_dict["factor"] == scaling["factor"]
    ids, mask = _inputs(seq=32)    # past original_max_position_embeddings
    m = transformers.LlamaForCausalLM.from_pretrained(d).eval()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids),
                  attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)

    # export round-trips the scaling config
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, "llama", mcfg)
    _, _, _, cfg2 = auto_models.from_pretrained(out, task="causal-lm")
    assert cfg2.rope_scaling_dict == mcfg.rope_scaling_dict


def test_rope_scaling_unknown_type_rejected(tmp_path):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        llama_config_from_hf,
    )

    with pytest.raises(ValueError, match="yarn"):
        llama_config_from_hf({"model_type": "llama", "vocab_size": 64,
                              "hidden_size": 16, "num_hidden_layers": 1,
                              "num_attention_heads": 2,
                              "intermediate_size": 32,
                              "rope_scaling": {"rope_type": "yarn",
                                               "factor": 2.0}})
