"""Decoder-only beam search (models/generate.py::beam_search_causal).

HF ``model.generate(num_beams=K, do_sample=False)`` parity for GPT-2
and Llama on the same weights: the 2K-candidate grid, add-time length
penalty over the GENERATED length (modern ``BeamSearchScorer``
normalizes by ``cur_len - decoder_prompt_len``), the finished
-hypothesis pool, and is_done bookkeeping must all agree token-for
-token — and the sequences_scores must match numerically, which pins
the normalization choice.
"""

import numpy as np
import pytest
import torch
import transformers

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models import generate as gen


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        n_inner=64, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        bos_token_id=1, eos_token_id=2)
    d = str(tmp_path_factory.mktemp("gpt2_beam"))
    m = transformers.GPT2LMHeadModel(cfg).eval()
    m.save_pretrained(d)
    return d, m


@pytest.fixture(scope="module")
def llama_dir(tmp_path_factory):
    torch.manual_seed(1)
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False, attention_dropout=0.0)
    d = str(tmp_path_factory.mktemp("llama_beam"))
    m = transformers.LlamaForCausalLM(cfg).eval()
    m.save_pretrained(d)
    return d, m


@pytest.mark.parametrize("num_beams,length_penalty,seed", [
    (2, 1.0, 0), (4, 1.0, 1), (4, 0.6, 2), (3, 2.0, 3),
])
def test_gpt2_beam_matches_hf(gpt2_dir, num_beams, length_penalty, seed):
    d, m = gpt2_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="causal-lm")
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, 96, (2, 6))
    ours = np.asarray(gen.beam_search_causal(
        model, params, ids, num_beams=num_beams, max_new_tokens=6,
        length_penalty=length_penalty))
    with torch.no_grad():
        theirs = m.generate(input_ids=torch.tensor(ids),
                            attention_mask=torch.ones_like(
                                torch.tensor(ids)),
                            max_new_tokens=6, do_sample=False,
                            num_beams=num_beams,
                            length_penalty=length_penalty,
                            early_stopping=False,
                            pad_token_id=0).numpy()
    for b in range(ids.shape[0]):
        hf_cont = theirs[b][ids.shape[1]:]          # continuation only
        n = min(len(hf_cont), ours.shape[1])
        np.testing.assert_array_equal(ours[b][:n], hf_cont[:n])


@pytest.mark.parametrize("num_beams,seed", [(2, 0), (4, 5)])
def test_llama_beam_matches_hf(llama_dir, num_beams, seed):
    d, m = llama_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="causal-lm")
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, 96, (2, 5))
    ours = np.asarray(gen.beam_search_causal(
        model, params, ids, num_beams=num_beams, max_new_tokens=5))
    with torch.no_grad():
        theirs = m.generate(input_ids=torch.tensor(ids),
                            attention_mask=torch.ones_like(
                                torch.tensor(ids)),
                            max_new_tokens=5, do_sample=False,
                            num_beams=num_beams, early_stopping=False,
                            pad_token_id=0).numpy()
    for b in range(ids.shape[0]):
        hf_cont = theirs[b][ids.shape[1]:]
        n = min(len(hf_cont), ours.shape[1])
        np.testing.assert_array_equal(ours[b][:n], hf_cont[:n])


def test_beam1_matches_greedy(llama_dir):
    """K=1 beam search must reduce to greedy when nothing hits EOS."""
    d, _ = llama_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    rng = np.random.RandomState(7)
    ids = rng.randint(3, 96, (2, 5))
    greedy = np.asarray(gen.generate_causal(model, params, ids,
                                            max_new_tokens=6))
    if (greedy == 2).any():
        pytest.skip("greedy rollout hit EOS for this init; the "
                    "K=1-equals-greedy equivalence needs an EOS-free run")
    beam = np.asarray(gen.beam_search_causal(model, params, ids,
                                             num_beams=1,
                                             max_new_tokens=6))
    np.testing.assert_array_equal(beam, greedy)


def test_gpt2_beam_scores_match_hf(gpt2_dir):
    """sequences_scores parity pins the GENERATED-length normalization
    (modern HF divides by generated_len, not the full sequence — a
    full-length denominator would be off by ((P+T)/T)**penalty)."""
    d, m = gpt2_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    ids = np.random.RandomState(4).randint(3, 96, (2, 6))
    ours, scores = gen.beam_search_causal(
        model, params, ids, num_beams=4, max_new_tokens=6,
        length_penalty=2.0, return_scores=True)
    with torch.no_grad():
        hf = m.generate(input_ids=torch.tensor(ids),
                        attention_mask=torch.ones_like(torch.tensor(ids)),
                        max_new_tokens=6, do_sample=False, num_beams=4,
                        length_penalty=2.0, early_stopping=False,
                        pad_token_id=0, return_dict_in_generate=True,
                        output_scores=True)
    np.testing.assert_allclose(np.asarray(scores),
                               hf.sequences_scores.numpy(), atol=2e-4)


def test_gpt2_beam_with_eos_banked_matches_hf(gpt2_dir):
    """Find a prompt whose HF beam output banks an EOS hypothesis
    mid-generation (hypotheses of DIFFERENT lengths in the pool), then
    demand token parity — the case where a wrong length-penalty
    denominator would pick a different winner."""
    d, m = gpt2_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    found = None
    for seed in range(60):
        ids = np.random.RandomState(100 + seed).randint(3, 96, (1, 6))
        with torch.no_grad():
            hf = m.generate(input_ids=torch.tensor(ids),
                            attention_mask=torch.ones_like(
                                torch.tensor(ids)),
                            max_new_tokens=8, do_sample=False,
                            num_beams=4, length_penalty=0.6,
                            early_stopping=False, pad_token_id=0).numpy()
        cont = hf[0][ids.shape[1]:]
        if (cont == 2).any() and cont[-1] == 0:   # EOS banked, then pads
            found = (ids, cont)
            break
    if found is None:
        pytest.skip("no EOS-banking prompt found for this init")
    ids, cont = found
    ours = np.asarray(gen.beam_search_causal(
        model, params, ids, num_beams=4, max_new_tokens=8,
        length_penalty=0.6))
    n = min(len(cont), ours.shape[1])
    np.testing.assert_array_equal(ours[0][:n], cont[:n])


def test_beam_causal_rejects_moe():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=2,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=64, num_experts=2,
                      model_type="mixtral")
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg)
    with pytest.raises(ValueError, match="capacity"):
        gen.beam_search_causal(model, params, np.ones((1, 4), np.int64))


def test_beam_composes_with_int8_kv(llama_dir):
    """Beam search's per-step cache gather must carry the int8 scale
    leaves along with the quantized buffers — beam under the int8 cache
    equals beam under the fp cache on the tiny model."""
    d, _ = llama_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    model_q, params_q, _, _ = auto_models.from_pretrained(
        d, task="causal-lm", kv_cache_dtype="int8")
    rng = np.random.RandomState(9)
    ids = rng.randint(3, 96, (2, 5))
    want = np.asarray(gen.beam_search_causal(model, params, ids,
                                             num_beams=3,
                                             max_new_tokens=6))
    got = np.asarray(gen.beam_search_causal(model_q, params_q, ids,
                                            num_beams=3,
                                            max_new_tokens=6))
    np.testing.assert_array_equal(got, want)
