"""Pallas fused paged-attention decode kernel
(``ops/pallas_paged_attention.py``) vs the XLA gather reference
(``ops.attention.paged_attention(impl='xla')``), run in interpret mode
on CPU — the hardware-free correctness story the ISSUE 9 acceptance
names: width buckets × GQA groupings × fp/int8 pools × sliding-window
bands. Plus the int8 scatter/gather scale-path contracts the pools are
built on: quantize→scatter→gather/dequant roundtrip error bounds, the
null-block-0 zero-scale convention, and COW copying the int8 block AND
its scale rows atomically."""

import numpy as np
import pytest


def _pools(rng, N, bs, Hkv, D):
    import jax.numpy as jnp

    pk = jnp.asarray(rng.randn(N, bs, Hkv, D).astype(np.float32))
    pv = jnp.asarray(rng.randn(N, bs, Hkv, D).astype(np.float32))
    return pk, pv


def _quantized(rng, pool):
    """An int8 pool + positive scale plane whose dequantized value is
    the reference fp pool for parity checks."""
    import jax.numpy as jnp

    scale = jnp.asarray(
        0.05 + np.abs(rng.randn(*pool.shape[:3], 1)).astype(np.float32))
    q = jnp.clip(jnp.round(pool / scale), -127, 127).astype(jnp.int8)
    return q, scale, q.astype(jnp.float32) * scale


def _xla_ref(q, pk, pv, tables, ctx, width=None, window=None):
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        paged_attention,
    )

    return paged_attention(q, pk, pv, tables, ctx, width=width,
                           impl="xla", window=window)


def _kernel(q, pk, pv, tables, ctx, width=None, window=None, ks=None,
            vs=None):
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.pallas_paged_attention import (
        paged_decode_attention,
    )

    return paged_decode_attention(q, pk, pv, tables, ctx, width=width,
                                  window=window, k_scale_pool=ks,
                                  v_scale_pool=vs)


def _assert_close(got, want, ctx):
    """Active rows match to tolerance; the kernel's context-0 rows are
    exact zeros (the XLA path emits masked-junk softmax there — both
    discarded by callers)."""
    act = np.asarray(ctx) > 0
    np.testing.assert_allclose(np.asarray(got)[act], np.asarray(want)[act],
                               rtol=1e-5, atol=1e-5)
    assert np.all(np.asarray(got)[~act] == 0.0)


def test_paged_kernel_smoke_matches_xla():
    """Tier-1 smoke: one small fp GQA case through the kernel (tiny
    width, one bucket) — the full matrix runs under the slow tier."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    S, Hq, Hkv, D, bs, nb = 3, 4, 2, 8, 4, 4
    pk, pv = _pools(rng, 1 + S * nb, bs, Hkv, D)
    tables = jnp.asarray(rng.permutation(np.arange(1, 1 + S * nb))
                         .reshape(S, nb).astype(np.int32))
    q = jnp.asarray(rng.randn(S, Hq, D).astype(np.float32))
    ctx = jnp.asarray(np.array([5, 16, 0], np.int32))
    got = _kernel(q, pk, pv, tables, ctx, width=16)
    want = _xla_ref(q, pk, pv, tables, ctx, width=16)
    _assert_close(got, want, ctx)


@pytest.mark.parametrize("group", [1, 4])
def test_paged_kernel_matrix_matches_xla(group):
    """The acceptance matrix: every (width bucket × sliding window)
    combination, fp AND int8 pools, at GQA group sizes 1 (MHA) and 4 —
    kernel output == XLA gather path to tolerance on active rows."""
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    Hkv, D, bs, nb = 2, 16, 4, 8
    Hq = Hkv * group
    S = 5
    N = 1 + S * nb
    pk, pv = _pools(rng, N, bs, Hkv, D)
    qk, ks, dk = _quantized(rng, pk)
    qv, vs, dv = _quantized(rng, pv)
    tables = jnp.asarray(rng.permutation(np.arange(1, N))
                         .reshape(S, nb).astype(np.int32))
    q = jnp.asarray(rng.randn(S, Hq, D).astype(np.float32))
    base = np.array([1, 7, 13, 32, 0], np.int32)
    for width in (None, 8, 16):
        W = width or bs * nb
        ctx = jnp.asarray(np.minimum(base, W))
        for window in (None, 3, 11):
            got = _kernel(q, pk, pv, tables, ctx, width=width,
                          window=window)
            want = _xla_ref(q, pk, pv, tables, ctx, width=width,
                            window=window)
            _assert_close(got, want, ctx)
            # int8 pools: in-kernel dequant == dequantize-then-attend
            got8 = _kernel(q, qk, qv, tables, ctx, width=width,
                           window=window, ks=ks, vs=vs)
            want8 = _xla_ref(q, dk, dv, tables, ctx, width=width,
                             window=window)
            _assert_close(got8, want8, ctx)


def test_paged_kernel_validates_inputs():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        paged_attention,
    )

    rng = np.random.RandomState(2)
    pk, pv = _pools(rng, 9, 4, 2, 8)
    tables = jnp.zeros((2, 2), jnp.int32)
    ctx = jnp.zeros((2,), jnp.int32)
    q3 = jnp.asarray(rng.randn(2, 3, 8).astype(np.float32))
    with pytest.raises(ValueError, match="multiple of pool kv heads"):
        _kernel(q3, pk, pv, tables, ctx)
    q = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))
    with pytest.raises(ValueError, match="multiple"):
        _kernel(q, pk, pv, tables, ctx, width=6)
    with pytest.raises(ValueError, match="block table holds"):
        _kernel(q, pk, pv, tables, ctx, width=16)
    with pytest.raises(ValueError, match="BOTH"):
        _kernel(q, pk, pv, tables, ctx, ks=jnp.zeros((9, 4, 2, 1)))
    with pytest.raises(ValueError, match="unknown paged_attention impl"):
        paged_attention(q, pk, pv, tables, ctx, impl="cuda")


# -- int8 scatter/gather scale path (the pools the kernel reads) -------------

def test_int8_scatter_gather_roundtrip_error_bound():
    """quantize → scatter (values + scales) → gather/dequant recovers
    the original K/V within the symmetric-int8 bound (scale/2 per
    element), and EXACTLY at zero."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        kv_quantize,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        gather_paged_kv,
        scatter_paged_kv,
    )

    rng = np.random.RandomState(3)
    B, H, D, bs, nb = 2, 3, 8, 4, 2
    N = 1 + B * nb
    pool = jnp.zeros((N, bs, H, D), jnp.int8)
    scale_pool = jnp.zeros((N, bs, H, 1), jnp.float32)
    tables = jnp.asarray(np.arange(1, N).reshape(B, nb).astype(np.int32))
    vals = rng.randn(B, H, bs * nb, D).astype(np.float32) * 3.0
    vals[0, :, 2] = 0.0                        # a zero row stays exact
    for p in range(bs * nb):
        x = jnp.asarray(vals[:, :, p:p + 1, :])     # [B, H, 1, D]
        qx, sx = kv_quantize(x)
        pos = jnp.full((B,), p, jnp.int32)
        pool = scatter_paged_kv(pool, tables, pos, qx[:, :, 0, :])
        scale_pool = scatter_paged_kv(scale_pool, tables, pos,
                                      sx[:, :, 0, :])
    got = (np.asarray(gather_paged_kv(pool, tables)).astype(np.float32)
           * np.asarray(gather_paged_kv(scale_pool, tables)))
    scales = np.abs(vals).max(axis=-1, keepdims=True) / 127.0
    assert np.all(np.abs(got - vals) <= scales / 2 + 1e-7)
    np.testing.assert_array_equal(got[0, :, 2], 0.0)


def test_null_block_zero_scale_convention():
    """Block 0 (the null block inactive slots scatter to) starts at
    int8 0 with scale 0: a gather that reads it dequantizes to EXACT
    zeros, never junk — and writes routed there never touch real
    blocks."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        gather_paged_kv,
        scatter_paged_kv,
    )

    pool = jnp.zeros((4, 2, 2, 4), jnp.int8)
    scale_pool = jnp.zeros((4, 2, 2, 1), jnp.float32)
    real = pool.at[2].set(7)
    # an inactive slot's write routed to the null table row
    null_tables = jnp.zeros((1, 2), jnp.int32)
    written = scatter_paged_kv(real, null_tables,
                               jnp.zeros((1,), jnp.int32),
                               jnp.full((1, 2, 4), 5, jnp.int8))
    assert np.all(np.asarray(written[2]) == 7)          # real untouched
    deq = (np.asarray(gather_paged_kv(pool, null_tables))
           .astype(np.float32)
           * np.asarray(gather_paged_kv(scale_pool, null_tables)))
    np.testing.assert_array_equal(deq, 0.0)


def test_cow_copies_int8_block_and_scale_rows_atomically():
    """The engine's COW device copy must duplicate EVERY pool a block
    addresses — under int8 that is the int8 K/V pools AND their fp32
    scale pools in the same ``_apply_cow`` application, or a privatized
    block would dequantize with another request's scales."""
    import dataclasses

    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg = Gpt2Config(vocab_size=64, hidden_size=16, num_layers=1,
                     num_heads=2, intermediate_size=32,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=63, pad_token_id=0,
                     kv_cache_dtype="int8")
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg, seed=0)
    eng = ServeEngine(model, params, num_slots=2, block_size=4,
                      num_blocks=8, prefill_chunk=4, max_model_len=16,
                      prefix_cache=True)
    dtypes = {str(p.dtype) for p in eng._pools}
    assert dtypes == {"int8", "float32"}       # values + scale planes
    # poison block 1 across every pool, then COW-copy it to block 2
    eng._pools = [p.at[1].set(3 if p.dtype == jnp.int8 else 0.5)
                  for p in eng._pools]

    class _Slot:
        pending_copies = [(1, 2)]

    slot = _Slot()
    eng._apply_cow(slot)
    assert slot.pending_copies == []
    for p in eng._pools:
        np.testing.assert_array_equal(np.asarray(p[2]), np.asarray(p[1]))
