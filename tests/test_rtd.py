"""Replaced-token-detection task (ELECTRA pretraining): HF parity for
the discriminator head, corpus corruption statistics, e2e training."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (  # noqa: E402
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (  # noqa: E402
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer  # noqa: E402


def test_rtd_head_parity(tmp_path):
    torch.manual_seed(0)
    cfg = transformers.ElectraConfig(
        vocab_size=128, hidden_size=32, embedding_size=16,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    m = transformers.ElectraForPreTraining(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "electra")
    m.save_pretrained(d)
    model, params, fam, _ = auto_models.from_pretrained(d, task="rtd")
    assert fam == "electra"
    r = np.random.RandomState(0)
    ids = r.randint(4, 128, (3, 12))
    mask = np.ones((3, 12), np.int64)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=2e-4, rtol=1e-3)


def test_rtd_corpus_statistics():
    tok = WordHashTokenizer(vocab_size=1024)
    texts = ["the quick brown fox jumps over the lazy dog " * 4] * 50
    ds = ArrayDataset.from_rtd_texts(tok, texts, max_length=48, seed=0)
    ids = ds.columns["input_ids"]
    labels = ds.columns["labels"]
    am = ds.columns["attention_mask"]
    # specials/pads ignored; real tokens labeled 0/1
    assert set(np.unique(labels)) <= {-100, 0, 1}
    real = labels != -100
    frac = (labels == 1).sum() / real.sum()
    assert 0.08 < frac < 0.22
    # replaced positions actually differ from the clean encoding
    clean = tok(texts, max_length=48)["input_ids"]
    changed = (ids != clean) & real
    np.testing.assert_array_equal(changed, labels == 1)
    # pads/specials are -100
    assert np.all(labels[am == 0] == -100)


def test_rtd_training_learns(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.electra import (
        ElectraForPreTraining,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_rtd_texts(tok, texts, max_length=16, seed=0)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    model_cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                              num_heads=4, intermediate_size=64,
                              max_position_embeddings=16, hidden_dropout=0.0,
                              attention_dropout=0.0, use_pooler=False)
    model = ElectraForPreTraining(model_cfg)
    params = init_params(model, model_cfg)
    cfg = TrainConfig(task="rtd", dtype="float32", learning_rate=5e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=3)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.95


def test_electra_generator_mlm_parity(tmp_path):
    """ELECTRA's generator MLM head (the other half of its pretraining);
    weights perturbed so dropped params can't hide behind fresh init."""
    torch.manual_seed(1)
    cfg = transformers.ElectraConfig(
        vocab_size=128, hidden_size=32, embedding_size=16,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    m = transformers.ElectraForMaskedLM(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "gen")
    m.save_pretrained(d)
    model, params, fam, _ = auto_models.from_pretrained(d, task="mlm")
    assert fam == "electra"
    r = np.random.RandomState(0)
    ids = r.randint(4, 128, (3, 12))
    mask = np.ones((3, 12), np.int64)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=2e-4, rtol=1e-3)
