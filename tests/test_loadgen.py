"""Open-loop load generation + SLO contract (ISSUE 16,
``serve/loadgen.py`` + the engine's deadline fields): the arrival
generators are pure functions of their seeds, the virtual-clock driver
is byte-replayable (the property the bench's determinism gates rest
on — including Router ``replicas=1`` vs the bare engine), overload is
queue-attributed, and every new telemetry field stays ABSENT on a
closed-loop run (the byte-identity contract for pre-16 streams)."""

import json

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.loadgen import (
    ENV_ARRIVAL,
    ENV_ARRIVAL_SEED,
    ENV_SLO_TPOT,
    ENV_SLO_TTFT,
    OpenLoopDriver,
    SloSpec,
    bursty_arrivals,
    heavy_tailed_lengths,
    make_schedule,
    parse_arrival,
    parse_arrival_seed,
    parse_slo,
    poisson_arrivals,
)


# -- generators (pure host) --------------------------------------------------

def test_poisson_arrivals_deterministic_monotone():
    a = poisson_arrivals(10.0, 50, seed=3)
    assert a == poisson_arrivals(10.0, 50, seed=3)
    assert a != poisson_arrivals(10.0, 50, seed=4)
    assert len(a) == 50
    assert all(b > c for b, c in zip(a[1:], a))    # strictly increasing
    with pytest.raises(ValueError):
        poisson_arrivals(0.0, 5)


def test_bursty_arrivals_deterministic_and_bursty():
    a = bursty_arrivals(50.0, 1.0, 0.2, 80, seed=7)
    assert a == bursty_arrivals(50.0, 1.0, 0.2, 80, seed=7)
    assert all(b > c for b, c in zip(a[1:], a))
    # two very different state rates must leave a visible gap spread —
    # the burst/lull signature a rate-matched plain Poisson lacks
    gaps = sorted(b - c for b, c in zip(a[1:], a))
    assert gaps[-1] / max(gaps[0], 1e-12) > 10
    with pytest.raises(ValueError):
        bursty_arrivals(5.0, 0.0, 0.1, 5)
    with pytest.raises(ValueError):
        bursty_arrivals(5.0, 1.0, 1.5, 5)


def test_heavy_tailed_lengths_bounded_deterministic():
    ls = heavy_tailed_lengths(200, 4, 64, seed=1, alpha=1.2)
    assert ls == heavy_tailed_lengths(200, 4, 64, seed=1, alpha=1.2)
    assert all(4 <= v <= 64 for v in ls)
    # bounded Pareto: mass near lo, tail reaching high
    assert sorted(ls)[len(ls) // 2] < 16 < max(ls)
    with pytest.raises(ValueError):
        heavy_tailed_lengths(5, 0, 8)
    with pytest.raises(ValueError):
        heavy_tailed_lengths(5, 4, 8, alpha=0.0)


def test_make_schedule_deterministic_sorted_and_grouped():
    kw = dict(process="bursty", rate=40.0, rate_lo=4.0, p_switch=0.3,
              seed=9, prompt_lo=2, prompt_hi=6, new_lo=2, new_hi=5,
              eos_token_id=63, groups=("a", "b", "c"))
    sched = make_schedule(12, 64, **kw)
    assert sched == make_schedule(12, 64, **kw)
    assert [t for t, _ in sched] == sorted(t for t, _ in sched)
    for i, (_, spec) in enumerate(sched):
        assert 2 <= len(spec["prompt"]) <= 6
        assert 2 <= spec["max_new_tokens"] <= 5
        assert 63 not in spec["prompt"]            # eos never in prompts
        assert spec["group"] == ("a", "b", "c")[i % 3]
    with pytest.raises(ValueError):
        make_schedule(4, 64, process="uniform")


# -- knob parsing ------------------------------------------------------------

def test_slospec_validation():
    assert SloSpec(ttft_s=0.5).tpot_s is None
    with pytest.raises(ValueError):
        SloSpec()                                  # no target at all
    with pytest.raises(ValueError):
        SloSpec(ttft_s=0.0)
    with pytest.raises(ValueError):
        SloSpec(tpot_s=-1.0)


def test_parse_arrival_specs_and_env(monkeypatch):
    assert parse_arrival("closed") is None
    assert parse_arrival("poisson:2.5") == ("poisson", {"rate": 2.5})
    assert parse_arrival("bursty:4,0.5,0.25") == (
        "bursty", {"rate_hi": 4.0, "rate_lo": 0.5, "p_switch": 0.25})
    for bad in ("poisson", "poisson:0", "bursty:1,2", "wat:1"):
        with pytest.raises(ValueError):
            parse_arrival(bad)
    monkeypatch.delenv(ENV_ARRIVAL, raising=False)
    assert parse_arrival() is None                 # default: closed
    monkeypatch.setenv(ENV_ARRIVAL, "poisson:8")
    assert parse_arrival() == ("poisson", {"rate": 8.0})


def test_parse_arrival_seed_env(monkeypatch):
    monkeypatch.delenv(ENV_ARRIVAL_SEED, raising=False)
    assert parse_arrival_seed() == 0
    monkeypatch.setenv(ENV_ARRIVAL_SEED, "42")
    assert parse_arrival_seed() == 42
    with pytest.raises(ValueError):
        parse_arrival_seed("x")


def test_parse_slo_specs_and_env(monkeypatch):
    assert parse_slo("none") is None
    assert parse_slo("ttft:0.5") == SloSpec(ttft_s=0.5)
    assert parse_slo("tpot:0.05,ttft:0.5") == SloSpec(ttft_s=0.5,
                                                      tpot_s=0.05)
    for bad in ("ttft:x", "ttft:0.5,ttft:1", "p99:1"):
        with pytest.raises(ValueError):
            parse_slo(bad)
    monkeypatch.delenv(ENV_SLO_TTFT, raising=False)
    monkeypatch.delenv(ENV_SLO_TPOT, raising=False)
    assert parse_slo() is None                     # default: no SLO
    monkeypatch.setenv(ENV_SLO_TTFT, "0.25")
    assert parse_slo() == SloSpec(ttft_s=0.25)


# -- the virtual-clock driver on the real engine -----------------------------

@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


_ENGINE_KW = dict(num_slots=2, block_size=8, num_blocks=17,
                  prefill_chunk=8, max_model_len=64, timeline="off")


def _schedule(rate=50.0):
    return make_schedule(6, 128, process="poisson", rate=rate, seed=3,
                         prompt_lo=4, prompt_hi=10, new_lo=3, new_hi=6,
                         eos_token_id=127, groups=("a", "b"))


def _drive(model, params, schedule, slo, out_dir=None, target="engine",
           rate=None):
    """One virtual-clock open-loop run on a fresh target; returns
    (outputs-in-submission-order, driver summary, raw serve events)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
        Router,
    )

    events = []
    if out_dir is not None:
        obs.reset(out_dir=str(out_dir), enabled=True)
    try:
        if target == "engine":
            t = ServeEngine(model, params, **_ENGINE_KW)
        else:
            t = Router(model, params, replicas=1,
                       placement="round_robin", **_ENGINE_KW)
        drv = OpenLoopDriver(t, schedule, clock="virtual", tick_s=0.001,
                             slo=slo, process="poisson", rate=rate)
        finished = drv.run()
        outs = [list(finished[rid].output) for rid in sorted(finished)]
        summary = drv.summary()
        if out_dir is not None:
            obs.flush()
            for line in (out_dir / "events.jsonl").read_text(
                    encoding="utf-8").splitlines():
                rec = json.loads(line)
                if rec.get("type") == "serve":
                    events.append(rec)
    finally:
        if out_dir is not None:
            obs.reset()
    return outs, summary, events


def _normalize(events):
    """The deterministic projection of a serve event stream: event
    kinds, submission-order request indices, token payloads and the
    integer backlog rider — everything except wall-clock stamps, which
    virtual mode deliberately leaves wall-domain."""
    rids = {}
    out = []
    for e in events:
        rid = e.get("request")
        if isinstance(rid, int) and rid not in rids:
            rids[rid] = len(rids)
        row = {"event": e.get("event")}
        if isinstance(rid, int):
            row["request"] = rids[rid]
        for k in ("token", "tokens", "arrival_backlog", "requests",
                  "process", "clock", "rate"):
            if k in e:
                row[k] = e[k]
        out.append(row)
    return out


def test_virtual_replay_is_byte_identical(gpt2_setup, tmp_path):
    """Same seed + schedule => token-identical outputs, byte-identical
    driver summaries, and identical normalized event streams — across
    reruns AND across Router(replicas=1) vs the bare engine (the
    passthrough contract)."""
    _, model, params = gpt2_setup
    slo = SloSpec(ttft_s=0.02, tpot_s=0.01)
    runs = [
        _drive(model, params, _schedule(), slo, tmp_path / "a",
               target="engine", rate=50.0),
        _drive(model, params, _schedule(), slo, tmp_path / "b",
               target="engine", rate=50.0),
        _drive(model, params, _schedule(), slo, tmp_path / "c",
               target="router", rate=50.0),
    ]
    outs0, sum0, ev0 = runs[0]
    assert all(len(o) > 0 for o in outs0)
    assert sum0["slo_attainment"] == 1.0           # underload holds
    assert sum0["clock"] == "virtual"
    for outs, summary, events in runs[1:]:
        assert outs == outs0
        assert (json.dumps(summary, sort_keys=True)
                == json.dumps(sum0, sort_keys=True))
        assert _normalize(events) == _normalize(ev0)
    # the open_loop stamp leads each stream, and every submit carries
    # its arrival stamp (the backlog ledger rider needs timeline="on";
    # the schema fixtures in test_obsctl cover that shape)
    assert ev0[0]["event"] == "open_loop"
    assert ev0[0]["process"] == "poisson" and ev0[0]["requests"] == 6
    assert all("arrival_s" in e for e in ev0 if e["event"] == "submit")


def test_virtual_overload_is_queue_dominant(gpt2_setup):
    """At a rate far past fleet capacity the driver's verdict must be
    the open-loop signature: attainment strictly below 1 with QUEUE the
    dominant miss phase, and the engine's deterministic backlog peak
    above zero."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    _, model, params = gpt2_setup
    eng = ServeEngine(model, params, **_ENGINE_KW)
    drv = OpenLoopDriver(eng, _schedule(rate=100000.0), clock="virtual",
                         tick_s=0.001, slo=SloSpec(ttft_s=0.003),
                         process="poisson", rate=100000.0)
    drv.run()
    s = drv.summary()
    assert 0.0 < s["slo_attainment"] < 1.0
    assert s["dominant_miss_phase"] == "queue"
    assert s["miss_phases"]["queue"] == s["slo_missed"]
    assert set(s["group_slo_attainment"]) == {"a", "b"}
    assert eng.slo_summary()["arrival_backlog_peak"] > 0


def test_driver_is_one_shot(gpt2_setup):
    _, model, params = gpt2_setup
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    eng = ServeEngine(model, params, **_ENGINE_KW)
    drv = OpenLoopDriver(eng, _schedule(), clock="virtual")
    drv.run()
    with pytest.raises(RuntimeError):
        drv.run()
    with pytest.raises(ValueError):
        OpenLoopDriver(eng, _schedule(), clock="sundial")
    with pytest.raises(ValueError):
        OpenLoopDriver(eng, _schedule(), tick_s=0.0)


# -- the engine's SLO contract -----------------------------------------------

def test_closed_loop_stream_has_no_new_fields(gpt2_setup, tmp_path):
    """Absent-when-default: a plain closed-loop run (no arrival_s, no
    slo) must emit a stream with NONE of the ISSUE 16 fields — the
    byte-identity contract for every pre-16 consumer."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    out = tmp_path / "closed"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        eng = ServeEngine(model=gpt2_setup[1], params=gpt2_setup[2],
                          **_ENGINE_KW)
        for _, spec in _schedule():
            eng.submit(spec["prompt"], spec["max_new_tokens"])
        eng.run()
        assert "slo_attainment" not in eng.slo_summary()
        assert "arrival_backlog_peak" not in eng.slo_summary()
        obs.flush()
    finally:
        obs.reset()
    new_fields = {"arrival_s", "slo_ttft_s", "slo_tpot_s", "slo_met",
                  "ttft_slo_met", "tpot_slo_met", "slack_s",
                  "slo_attainment", "group_slo_attainment",
                  "arrival_backlog", "arrival_backlog_peak"}
    for line in (out / "events.jsonl").read_text(
            encoding="utf-8").splitlines():
        rec = json.loads(line)
        if rec.get("type") == "serve":
            assert not new_fields & set(rec), rec


def test_wall_slo_verdicts_ride_the_stream(gpt2_setup, tmp_path):
    """slo= threaded into submit: finish events carry the verdict
    (slo_met / per-axis flags / slack), the report event the
    attainment + per-group split, and ledgers the arrival backlog."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    out = tmp_path / "wall"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        eng = ServeEngine(model=gpt2_setup[1], params=gpt2_setup[2],
                          **_ENGINE_KW)
        drv = OpenLoopDriver(eng, _schedule(rate=200.0), clock="wall",
                             slo=SloSpec(ttft_s=5.0, tpot_s=5.0),
                             process="poisson", rate=200.0)
        drv.run()
        assert eng.slo_summary()["slo_attainment"] == 1.0
        assert set(eng.slo_summary()["group_slo_attainment"]) == \
            {"a", "b"}
        obs.flush()
    finally:
        obs.reset()
    finishes = reports = submits = 0
    for line in (out / "events.jsonl").read_text(
            encoding="utf-8").splitlines():
        rec = json.loads(line)
        if rec.get("type") != "serve":
            continue
        if rec.get("event") == "finish":
            finishes += 1
            assert rec["slo_met"] is True
            assert rec["ttft_slo_met"] is True
            assert rec["tpot_slo_met"] is True
            assert rec["slack_s"] > 0
        elif rec.get("event") == "report":
            reports += 1
            assert rec["slo_attainment"] == 1.0
            assert "arrival_backlog_peak" in rec
        elif rec.get("event") == "submit":
            submits += 1
            assert rec["arrival_s"] > 0
            assert rec["slo_ttft_s"] == 5.0
    assert finishes == 6 and submits == 6 and reports == 1
