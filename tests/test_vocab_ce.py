"""Fused LM-head + cross-entropy parity (ops/pallas_vocab_ce.py).

Contract: loss, prediction, and BOTH gradients (dHidden, dWeight) match
the unfused full-logits path to fp32 roundoff — in interpret mode on
CPU, including a non-128-multiple vocab (padding masked in-kernel) and
multi-block token/vocab grids.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.ops.pallas_vocab_ce import (
    fused_vocab_cross_entropy,
)


def _unfused(hidden, weight, labels):
    logits = hidden.astype(jnp.float32) @ weight.astype(jnp.float32).T
    return (optax.softmax_cross_entropy_with_integer_labels(logits, labels),
            jnp.argmax(logits, -1))


def _rand(n_tok, h_dim, vocab, seed=0):
    rng = np.random.RandomState(seed)
    hidden = jnp.asarray(rng.randn(n_tok, h_dim).astype(np.float32))
    weight = jnp.asarray((rng.randn(vocab, h_dim) * 0.05).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, vocab, n_tok), jnp.int32)
    return hidden, weight, labels


@pytest.mark.parametrize("n_tok,vocab,block_n,block_v", [
    (256, 512, 128, 256),     # multi-block both axes
    (128, 1000, 128, 256),    # vocab NOT a multiple of block_v (padding)
    (384, 131, 128, 256),     # vocab < block_v, needs masked tail
])
def test_fused_matches_unfused_loss_and_pred(n_tok, vocab, block_n, block_v):
    hidden, weight, labels = _rand(n_tok, 128, vocab)
    want_loss, want_pred = _unfused(hidden, weight, labels)
    got_loss, got_pred = fused_vocab_cross_entropy(
        hidden, weight, labels, block_n=block_n, block_v=block_v,
        interpret=True)
    np.testing.assert_allclose(np.asarray(got_loss), np.asarray(want_loss),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_pred), np.asarray(want_pred))


def test_fused_gradients_match_unfused():
    hidden, weight, labels = _rand(256, 128, 777, seed=1)
    valid = jnp.asarray((np.arange(256) % 5 != 0).astype(np.float32))

    def loss_fused(h, w):
        per_tok, _ = fused_vocab_cross_entropy(h, w, labels, block_n=128,
                                               block_v=256, interpret=True)
        return jnp.sum(per_tok * valid) / jnp.sum(valid)

    def loss_unfused(h, w):
        per_tok, _ = _unfused(h, w, labels)
        return jnp.sum(per_tok * valid) / jnp.sum(valid)

    (gh_f, gw_f) = jax.grad(loss_fused, argnums=(0, 1))(hidden, weight)
    (gh_u, gw_u) = jax.grad(loss_unfused, argnums=(0, 1))(hidden, weight)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_u),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_u),
                               rtol=1e-4, atol=1e-5)


def test_argmax_tie_and_first_max_semantics():
    """Identical rows of W produce logit ties across vocab blocks; the
    fused argmax must pick the FIRST maximal id like jnp.argmax."""
    rng = np.random.RandomState(2)
    hidden = jnp.asarray(rng.randn(128, 128).astype(np.float32))
    row = (rng.randn(1, 128) * 0.05).astype(np.float32)
    weight = jnp.asarray(np.repeat(row, 512, axis=0))     # ALL rows equal
    labels = jnp.zeros(128, jnp.int32)
    _, pred = fused_vocab_cross_entropy(hidden, weight, labels, block_n=128,
                                        block_v=256, interpret=True)
    np.testing.assert_array_equal(np.asarray(pred), np.zeros(128))


def test_fallback_path_on_untileable_shapes():
    """N not a block multiple → XLA fallback, same results."""
    hidden, weight, labels = _rand(100, 64, 300, seed=3)
    want_loss, want_pred = _unfused(hidden, weight, labels)
    got_loss, got_pred = fused_vocab_cross_entropy(hidden, weight, labels,
                                                   interpret=True)
    np.testing.assert_allclose(np.asarray(got_loss), np.asarray(want_loss),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_pred), np.asarray(want_pred))


def test_fused_causal_lm_training_matches_unfused(devices8):
    """Trainer with fused_vocab_ce=True reproduces the unfused loss
    sequence on a dp8 mesh (shard_mapped kernel, psummed dW through the
    whole optimizer update). Tiny hidden (not 128-multiple) exercises
    the in-shard-map fallback; hidden=128 exercises the real kernel."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    seq = 16
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=7)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=seq)

    def run(fused, hidden_size):
        mesh = build_mesh(MeshConfig(dp=-1), devices=jax.devices())
        model_cfg = Gpt2Config(
            vocab_size=256, hidden_size=hidden_size, num_layers=2,
            num_heads=4, intermediate_size=2 * hidden_size,
            max_position_embeddings=seq, hidden_dropout=0.0,
            embd_dropout=0.0, attention_dropout=0.0)
        model = Gpt2LMHeadModel(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, fused_vocab_ce=fused,
                          rng_impl="threefry")
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            # force the real Pallas kernel (interpret mode) on this CPU
            # mesh — the default would take the unfused off-TPU fallback
            from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
                make_fused_causal_lm_loss,
            )
            trainer.loss_fn = make_fused_causal_lm_loss(model, interpret=True)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 3:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    for hs in (32, 128):
        np.testing.assert_allclose(run(True, hs), run(False, hs), rtol=2e-5,
                                   err_msg=f"hidden_size={hs}")


@pytest.mark.parametrize("family,tied", [("t5", True), ("t5", False),
                                         ("bart", True)])
def test_fused_seq2seq_training_matches_unfused(family, tied, devices8):
    """fused_vocab_ce for task='seq2seq': T5 (tied head with the
    d_model^-0.5 scaling, and the untied lm_head) and BART reproduce the
    unfused full-logits loss sequence on a dp8 mesh; hidden=128
    exercises the real kernel in interpret mode."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    src_len, tgt_len = 24, 16
    tok = WordHashTokenizer(vocab_size=256)
    sources, targets = synthetic_summarization(32, seed=4)
    ds = ArrayDataset.from_seq2seq(tok, sources, targets,
                                   max_source_length=src_len,
                                   max_target_length=tgt_len)

    def build_model():
        if family == "t5":
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
                T5Config,
                T5ForConditionalGeneration,
            )
            cfg = T5Config(vocab_size=256, d_model=128, num_layers=2,
                           num_decoder_layers=2, num_heads=4, d_ff=256,
                           d_kv=32, dropout_rate=0.0,
                           tie_word_embeddings=tied)
            return T5ForConditionalGeneration(cfg), cfg
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
            BartConfig,
            BartForConditionalGeneration,
        )
        cfg = BartConfig(vocab_size=256, d_model=128,
                         encoder_layers=2, decoder_layers=2,
                         encoder_attention_heads=4,
                         decoder_attention_heads=4,
                         encoder_ffn_dim=256, decoder_ffn_dim=256,
                         max_position_embeddings=64, dropout=0.0,
                         attention_dropout=0.0)
        return BartForConditionalGeneration(cfg), cfg

    def run(fused):
        mesh = build_mesh(MeshConfig(dp=-1), devices=jax.devices())
        model, model_cfg = build_model()
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="seq2seq", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, fused_vocab_ce=fused,
                          rng_impl="threefry")
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
                make_fused_seq2seq_loss,
            )
            trainer.loss_fn = make_fused_seq2seq_loss(model, interpret=True)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 2:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5)


def test_fused_mlm_training_matches_unfused(devices8):
    """fused_vocab_ce for task='mlm' (BERT-family): the sparse-gather +
    bias-folded kernel path reproduces the unfused full-logits loss
    sequence on a dp8 mesh. hidden=128 exercises the real kernel (via
    the 128-lane bias-augmentation, H→256); hidden=32 exercises the
    in-shard-map fallback. Also proves the decoder bias is handled
    exactly: the unfused MlmHead adds it to every logit."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForMaskedLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
        EncoderConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    seq = 16
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=3)
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=seq, seed=0)

    def run(fused, hidden_size):
        mesh = build_mesh(MeshConfig(dp=-1), devices=jax.devices())
        model_cfg = EncoderConfig(
            vocab_size=256, hidden_size=hidden_size, num_layers=2,
            num_heads=4, intermediate_size=2 * hidden_size,
            max_position_embeddings=seq, hidden_dropout=0.0,
            attention_dropout=0.0, use_pooler=False)
        model = BertForMaskedLM(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        # perturb the decoder bias away from zeros so bias mishandling
        # cannot hide
        params["mlm_head"]["bias"] = jnp.asarray(
            np.random.RandomState(5).randn(256) * 0.1, jnp.float32)
        cfg = TrainConfig(task="mlm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, fused_vocab_ce=fused,
                          rng_impl="threefry")
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
                make_fused_mlm_loss,
            )
            trainer.loss_fn = make_fused_mlm_loss(model, interpret=True)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 3:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    for hs in (32, 128):
        np.testing.assert_allclose(run(True, hs), run(False, hs), rtol=2e-5,
                                   err_msg=f"hidden_size={hs}")


def test_fused_seq2seq_composes_with_pipelined_t5(devices8):
    """The two r4 features compose: a PIPELINED T5 under
    --fused_vocab_ce trains with the same loss sequence as the pipelined
    model under the unfused full-logits loss (the fused path calls
    seq2seq_hidden_and_embedding, which routes through the pipelined
    decoder and its schedule riders)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    src_len, tgt_len = 16, 8
    tok = WordHashTokenizer(vocab_size=256)
    sources, targets = synthetic_summarization(32, seed=6)
    ds = ArrayDataset.from_seq2seq(tok, sources, targets,
                                   max_source_length=src_len,
                                   max_target_length=tgt_len)

    def run(fused):
        mesh = build_mesh(MeshConfig(dp=-1, pp=2), devices=jax.devices())
        model_cfg = T5Config(vocab_size=256, d_model=128, d_kv=32,
                             d_ff=256, num_layers=2, num_decoder_layers=2,
                             num_heads=4, dropout_rate=0.0,
                             pipeline_stages=2, pipeline_microbatches=4)
        model = T5ForConditionalGeneration(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="seq2seq", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, fused_vocab_ce=fused,
                          rng_impl="threefry", pp=2)
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
                make_fused_seq2seq_loss,
            )
            trainer.loss_fn = make_fused_seq2seq_loss(model, interpret=True)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 2:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5)


@pytest.mark.slow
def test_fused_label_smoothing_matches_unfused():
    """Smoothed fused CE: loss and both gradients must match the explicit
    (1-eps)*CE + eps*(lse - mean logits) computed from full logits —
    including with vocab padding (mean over REAL vocab only)."""
    eps = 0.1
    for vocab in (512, 1000):              # aligned and padded vocab
        hidden, weight, labels = _rand(256, 128, vocab, seed=2)
        valid = jnp.asarray((np.arange(256) % 3 != 0).astype(np.float32))

        def smooth_unfused(h, w):
            logits = h.astype(jnp.float32) @ w.astype(jnp.float32).T
            per_tok, _ = _unfused(h, w, labels)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            uniform = lse - jnp.mean(logits, axis=-1)
            per_tok = (1 - eps) * per_tok + eps * uniform
            return jnp.sum(per_tok * valid) / jnp.sum(valid)

        def smooth_fused(h, w):
            per_tok, _ = fused_vocab_cross_entropy(
                h, w, labels, block_n=128, block_v=256, interpret=True,
                label_smoothing=eps)
            return jnp.sum(per_tok * valid) / jnp.sum(valid)

        lu = float(smooth_unfused(hidden, weight))
        lf = float(smooth_fused(hidden, weight))
        assert lf == pytest.approx(lu, rel=1e-5), vocab
        (gh_f, gw_f) = jax.grad(smooth_fused, argnums=(0, 1))(hidden, weight)
        (gh_u, gw_u) = jax.grad(smooth_unfused, argnums=(0, 1))(hidden,
                                                                weight)
        np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_u),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_u),
                                   rtol=1e-4, atol=1e-5)
        # eps=0 keeps the original path bit-for-bit
        plain_f, _ = fused_vocab_cross_entropy(
            hidden, weight, labels, block_n=128, block_v=256,
            interpret=True, label_smoothing=0.0)
        plain_ref, _ = fused_vocab_cross_entropy(
            hidden, weight, labels, block_n=128, block_v=256,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(plain_f),
                                      np.asarray(plain_ref))


@pytest.mark.slow
def test_fused_seq2seq_label_smoothing_training_parity(devices8):
    """--fused_vocab_ce + --label_smoothing: the fused T5 training loss
    must equal the unfused smoothed loss on a dp8 mesh, and eval must
    drop the smoothing on both paths."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        make_fused_seq2seq_loss,
    )

    tok = WordHashTokenizer(vocab_size=256)
    sources, targets = synthetic_summarization(16, seed=4)
    ds = ArrayDataset.from_seq2seq(tok, sources, targets,
                                   max_source_length=24,
                                   max_target_length=16)

    def first_loss(fused, train=True):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        mcfg = T5Config(vocab_size=256, d_model=128, num_layers=2,
                        num_decoder_layers=2, num_heads=4, d_ff=256,
                        d_kv=32, dropout_rate=0.0)
        model = T5ForConditionalGeneration(mcfg)
        params = init_params(model, mcfg, seed=0)
        cfg = TrainConfig(task="seq2seq", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          label_smoothing=0.1, fused_vocab_ce=fused)
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            trainer.loss_fn = make_fused_seq2seq_loss(
                model, interpret=True, label_smoothing=0.1)
        batch = next(ShardedBatcher(ds, 16, mesh, shuffle=False,
                                    seed=0).global_arrays(0))
        if train:
            _, m = trainer._train_step(trainer.state, batch)
            return float(jax.device_get(m["loss"]))
        sums = trainer._eval_step(trainer.state.params, batch)
        s = jax.device_get(sums)
        return float(s["loss_sum"] / s["count"])

    np.testing.assert_allclose(first_loss(True), first_loss(False),
                               rtol=2e-5)
    # eval drops smoothing on both paths: fused-eval == unfused-eval
    np.testing.assert_allclose(first_loss(True, train=False),
                               first_loss(False, train=False), rtol=2e-5)
