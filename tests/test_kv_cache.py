"""int8 KV cache for Llama-family decode (models/llama.py).

Long-context decode is HBM-bound on the KV cache; storing K/V as
symmetric per-(head, slot) int8 + fp32 scales halves the bytes read per
step vs bf16. Contract: quantization error is bounded by the symmetric
-int8 step size, and greedy decode under the int8 cache stays
token-identical to the fp cache on the tiny test models (logit gaps
dwarf ~0.4% relative KV noise).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate_causal,
    generate_speculative,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    kv_quantize,
)


def _llama(seed=0, **kw):
    base = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                num_kv_heads=2, intermediate_size=64,
                max_position_embeddings=128)
    base.update(kw)
    cfg = LlamaConfig(**base)
    model = LlamaForCausalLM(cfg)
    return model, init_params(model, cfg, seed=seed)


def test_kv_quantize_error_bound_and_zero_rows():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 4, 8, 16) * 3.0, jnp.float32)
    q, scale = kv_quantize(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 4, 8, 1)
    deq = np.asarray(q, np.float32) * np.asarray(scale)
    # symmetric int8: error <= scale/2 per element
    err = np.abs(deq - np.asarray(x))
    assert (err <= np.asarray(scale) / 2 + 1e-7).all()
    # all-zero rows quantize to exact zeros (scale 0, no NaN)
    z = jnp.zeros((1, 1, 2, 16), jnp.float32)
    qz, sz = kv_quantize(z)
    assert np.asarray(qz).sum() == 0 and np.asarray(sz).sum() == 0
    assert np.isfinite(np.asarray(sz)).all()


@pytest.mark.parametrize("window", [None, 6], ids=["full", "mistral"])
def test_int8_kv_decode_matches_fp(window):
    """Greedy generation with the int8 cache == fp cache, including the
    sliding-window decode path (logical-position banding reads the same
    dequantized buffers)."""
    kw = {}
    if window is not None:
        kw = dict(sliding_window=window, model_type="mistral")
    _, params = _llama(seed=0, **kw)
    model_fp = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64,
        max_position_embeddings=128, **kw))
    model_q = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64,
        max_position_embeddings=128, kv_cache_dtype="int8", **kw))
    rng = np.random.RandomState(1)
    ids = rng.randint(3, 128, (2, 9))
    want = np.asarray(generate_causal(model_fp, params, ids,
                                      max_new_tokens=12))
    got = np.asarray(generate_causal(model_q, params, ids,
                                     max_new_tokens=12))
    np.testing.assert_array_equal(got, want)


def test_int8_kv_composes_with_speculative():
    """The speculative cache rewind only touches write indices, so the
    int8 scale buffers ride along — spec decode under int8 KV equals
    plain greedy under int8 KV."""
    cfg_q = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=3,
                        num_heads=4, num_kv_heads=2, intermediate_size=64,
                        max_position_embeddings=128, kv_cache_dtype="int8")
    target = LlamaForCausalLM(cfg_q)
    _, t_params = _llama(seed=0, num_layers=3)
    cfg_d = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=1,
                        num_heads=4, num_kv_heads=2, intermediate_size=64,
                        max_position_embeddings=128, kv_cache_dtype="int8")
    draft = LlamaForCausalLM(cfg_d)
    _, d_params = _llama(seed=1, num_layers=1)
    rng = np.random.RandomState(2)
    ids = rng.randint(3, 128, (1, 7))
    want = np.asarray(generate_causal(target, t_params, ids,
                                      max_new_tokens=10))
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          ids, max_new_tokens=10,
                                          speculate_k=3))
    np.testing.assert_array_equal(got, want)


def test_unknown_kv_cache_dtype_rejected():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
    )

    with pytest.raises(ValueError, match="kv_cache_dtype"):
        LlamaConfig(kv_cache_dtype="int4")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        Gpt2Config(kv_cache_dtype="int4")


def test_gpt2_int8_kv_decode_matches_fp():
    """Same contract on the GPT-2 cache convention."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    base = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=128,
                hidden_dropout=0.0, embd_dropout=0.0,
                attention_dropout=0.0)
    params = init_params(Gpt2LMHeadModel(Gpt2Config(**base)),
                         Gpt2Config(**base))
    model_fp = Gpt2LMHeadModel(Gpt2Config(**base))
    model_q = Gpt2LMHeadModel(Gpt2Config(**base, kv_cache_dtype="int8"))
    rng = np.random.RandomState(3)
    ids = rng.randint(3, 128, (2, 9))
    want = np.asarray(generate_causal(model_fp, params, ids,
                                      max_new_tokens=12))
    got = np.asarray(generate_causal(model_q, params, ids,
                                     max_new_tokens=12))
    np.testing.assert_array_equal(got, want)


def test_int8_kv_rejected_for_non_decoder_family(tmp_path):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
    )

    cfg = T5Config(vocab_size=64, d_model=16, d_kv=8, d_ff=32,
                   num_layers=1, num_decoder_layers=1, num_heads=2)
    params = init_params(T5ForConditionalGeneration(cfg), cfg)
    d = str(tmp_path / "t5")
    auto_models.save_pretrained(d, params, "t5", cfg)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        auto_models.from_pretrained(d, task="seq2seq",
                                    kv_cache_dtype="int8")
