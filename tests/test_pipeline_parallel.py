"""Pipeline-parallelism tests (models/pipeline.py, the ``pipe`` mesh
axis). Contract: the GPipe schedule is bit-compatible with the dense
Encoder (same math, different execution order), composes with dp/tp on
a real mesh, and round-trips HF checkpoints through the stacked layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
    stack_layer_params,
    unstack_layer_params,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    param_shardings,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 16
L = 4


def _cfg(pp=0, **kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=L, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                hidden_dropout=0.0, attention_dropout=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return EncoderConfig(**base)


def _inputs(batch=8):
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(5, 250, (batch, SEQ)), jnp.int32)
    mask = jnp.ones((batch, SEQ), jnp.int32)
    return ids, mask


def test_pipelined_matches_dense_forward():
    """Same weights (stacked from the dense model) → identical logits.
    The schedule is a re-ordering of the same math, so tolerance is
    float-roundoff only."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)

    pp_cfg = _cfg(pp=2)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    pp_params = jax.tree.map(lambda x: x, pp_params)  # mutable copy
    pp_params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        pp_params["backbone"][key] = dense_params["backbone"][key]
    pp_params["classifier"] = dense_params["classifier"]

    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_pipelined_grads_match_dense():
    """Backward through scan/roll produces the same gradients as the
    dense stack (mapped back through unstack)."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    pp_cfg = _cfg(pp=2, pipeline_microbatches=4)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    pp_params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        pp_params["backbone"][key] = dense_params["backbone"][key]
    pp_params["classifier"] = dense_params["classifier"]

    ids, mask = _inputs()

    def loss_dense(p):
        return jnp.sum(dense.apply({"params": p}, ids, mask,
                                   deterministic=True) ** 2)

    def loss_pp(p):
        return jnp.sum(piped.apply({"params": p}, ids, mask,
                                   deterministic=True) ** 2)

    g_dense = jax.grad(loss_dense)(dense_params)
    g_pp = jax.grad(loss_pp)(pp_params)
    g_pp_enc = unstack_layer_params(
        jax.tree.map(np.asarray, g_pp["backbone"]["pipelined_encoder"]), L)
    for i in range(L):
        np.testing.assert_allclose(
            g_pp_enc[f"layer_{i}"]["attention"]["query"]["kernel"],
            np.asarray(g_dense["backbone"]["encoder"][f"layer_{i}"]
                       ["attention"]["query"]["kernel"]),
            atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_pp["classifier"]["kernel"]),
        np.asarray(g_dense["classifier"]["kernel"]), atol=2e-4)


def test_stack_unstack_roundtrip():
    cfg = _cfg()
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    enc = params["backbone"]["encoder"]
    back = unstack_layer_params(stack_layer_params(enc, L), L)
    for i in range(L):
        np.testing.assert_array_equal(
            back[f"layer_{i}"]["ffn"]["intermediate"]["kernel"],
            np.asarray(enc[f"layer_{i}"]["ffn"]["intermediate"]["kernel"]))


def test_pp_mesh_training_matches_single_device(devices8):
    """dp2×pp2×tp2 training = single-device pipelined training: the pipe
    axis shards stages but must not change the update."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(32, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          rng_impl="threefry")
        model_cfg = _cfg(pp=2)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_pipelined_params_sharded_over_pipe(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, pp=2, tp=2), devices=devices8)
    model_cfg = _cfg(pp=2)
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    sh = param_shardings(params, mesh)
    enc = sh["backbone"]["pipelined_encoder"]
    assert enc["query_kernel"].spec == P("pipe", None, "tensor")
    assert enc["ffn_out_kernel"].spec == P("pipe", "tensor")
    assert enc["attention_ln_scale"].spec == P("pipe")


def test_hf_checkpoint_loads_into_pipelined_model(tmp_path):
    """Export a dense model, reload with pipeline_stages=2: forward must
    match the dense original (weights stacked on load)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    dense_cfg = _cfg()
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "dense")
    auto_models.save_pretrained(out, dense_params, "bert", dense_cfg)

    model, params, _, cfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2, pipeline_stages=2,
        hidden_dropout=0.0, attention_dropout=0.0)
    assert cfg.pipeline_stages == 2
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = model.apply({"params": params}, ids, mask, deterministic=True)
    # classifier head is freshly initialized on load, so compare the
    # backbone by re-using the dense head on the pipelined trunk: logits
    # differ, pooled trunk must not — compare via the exported encoder
    np.testing.assert_allclose(
        np.asarray(out_pp).shape, np.asarray(out_dense).shape)
    # strong check: stacked weights equal the dense ones
    stacked = stack_layer_params(dense_params["backbone"]["encoder"], L)
    for name, arr in stacked.items():
        np.testing.assert_allclose(
            np.asarray(params["backbone"]["pipelined_encoder"][name]), arr,
            atol=1e-6)


def test_pipelined_export_roundtrip(tmp_path):
    """save_pretrained of a pipelined model writes per-layer HF layout
    loadable as a dense model with identical weights."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    pp_cfg = _cfg(pp=2)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    out = str(tmp_path / "pp-export")
    auto_models.save_pretrained(out, pp_params, "bert", pp_cfg)

    _, dense_params, _, dense_cfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2)
    assert dense_cfg.pipeline_stages == 0
    stacked = pp_params["backbone"]["pipelined_encoder"]
    restacked = stack_layer_params(dense_params["backbone"]["encoder"], L)
    for name in restacked:
        np.testing.assert_allclose(restacked[name], np.asarray(stacked[name]),
                                   atol=1e-6)


def test_non_dividing_microbatches_degrade_to_gcd():
    """batch 8 with pipeline_microbatches=3 → effective M=1; outputs are
    M-invariant so results still match the dense model."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    cfg = _cfg(pp=2, pipeline_microbatches=3)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray, stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        params["backbone"][key] = dense_params["backbone"][key]
    params["classifier"] = dense_params["classifier"]
    ids, mask = _inputs(batch=8)
    out_pp = model.apply({"params": params}, ids, mask, deterministic=True)
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_dropout_runs_under_pipeline():
    """Non-deterministic path (per-tick/stage/layer folded keys) runs and
    produces different outputs across dropout keys."""
    cfg = _cfg(pp=2, hidden_dropout=0.5)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    ids, mask = _inputs()
    outs = [model.apply({"params": params}, ids, mask, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(k)})
            for k in (0, 1)]
    assert not np.allclose(np.asarray(outs[0]), np.asarray(outs[1]))


# --- GPT-2 (decoder-only family) under the same schedule ---------------------

from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (  # noqa: E402
    Gpt2Config,
    Gpt2LMHeadModel,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (  # noqa: E402
    GPT2_LAYER_LEAVES,
)


def _gpt2_cfg(pp=0, **kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=L, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                hidden_dropout=0.0, embd_dropout=0.0, attention_dropout=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return Gpt2Config(**base)


def _gpt2_pair():
    """(dense model+params, pipelined model+params with the SAME weights)."""
    dense_cfg = _gpt2_cfg(pp=0)
    dense = Gpt2LMHeadModel(dense_cfg)
    dense_params = init_params(dense, dense_cfg)

    pp_cfg = _gpt2_cfg(pp=2, pipeline_microbatches=4)
    piped = Gpt2LMHeadModel(pp_cfg)
    pp_params = init_params(piped, pp_cfg)
    bb = dense_params["backbone"]
    pp_params["backbone"]["pipelined_h"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params({k: bb[k] for k in bb if k.startswith("h_")}, L,
                           GPT2_LAYER_LEAVES, "h_{}"))
    for key in ("wte", "wpe", "ln_f"):
        pp_params["backbone"][key] = bb[key]
    return dense, dense_params, piped, pp_params


def test_gpt2_pipelined_matches_dense_forward():
    dense, dense_params, piped, pp_params = _gpt2_pair()
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_gpt2_pipelined_grads_match_dense():
    dense, dense_params, piped, pp_params = _gpt2_pair()
    ids, mask = _inputs()

    def loss_dense(p):
        return jnp.mean(dense.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    def loss_pp(p):
        return jnp.mean(piped.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    g_dense = jax.grad(loss_dense)(dense_params)
    g_pp = jax.grad(loss_pp)(pp_params)
    g_layers = unstack_layer_params(
        jax.tree.map(np.asarray, g_pp["backbone"]["pipelined_h"]), L,
        GPT2_LAYER_LEAVES, "h_{}")
    for i in range(L):
        np.testing.assert_allclose(
            g_layers[f"h_{i}"]["attention"]["qkv"]["kernel"],
            np.asarray(g_dense["backbone"][f"h_{i}"]["attention"]["qkv"]["kernel"]),
            atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_pp["backbone"]["wte"]["embedding"]),
        np.asarray(g_dense["backbone"]["wte"]["embedding"]), atol=2e-4)


def test_gpt2_pp_mesh_training_matches_single_device(devices8):
    """dp2×pp2×tp2 causal-lm training = single-device pipelined training."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=3)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry")
        model_cfg = _gpt2_cfg(pp=2)
        model = Gpt2LMHeadModel(model_cfg)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_gpt2_pipelined_params_sharded_over_pipe(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, pp=2, tp=2), devices=devices8)
    model_cfg = _gpt2_cfg(pp=2)
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg)
    sh = param_shardings(params, mesh)
    stacked = sh["backbone"]["pipelined_h"]
    assert stacked["qkv_kernel"].spec == P("pipe", None, "tensor")
    assert stacked["fc_out_kernel"].spec == P("pipe", "tensor")
    assert stacked["ln_1_scale"].spec == P("pipe")


def test_gpt2_hf_checkpoint_roundtrips_through_pipelined(tmp_path):
    """dense export → pipelined load (stacked weights match) → pipelined
    export → dense load (weights survive the full cycle)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    dense_cfg = _gpt2_cfg()
    dense = Gpt2LMHeadModel(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "gpt2-dense")
    auto_models.save_pretrained(out, dense_params, "gpt2", dense_cfg)

    model, params, fam, cfg = auto_models.from_pretrained(
        out, task="causal-lm", pipeline_stages=2,
        hidden_dropout=0.0, embd_dropout=0.0, attention_dropout=0.0)
    assert fam == "gpt2" and cfg.pipeline_stages == 2
    bb = dense_params["backbone"]
    stacked = stack_layer_params({k: bb[k] for k in bb if k.startswith("h_")},
                                 L, GPT2_LAYER_LEAVES, "h_{}")
    for name, arr in stacked.items():
        np.testing.assert_allclose(
            np.asarray(params["backbone"]["pipelined_h"][name]), arr,
            atol=1e-6)

    out2 = str(tmp_path / "gpt2-pp-export")
    auto_models.save_pretrained(out2, params, "gpt2", cfg)
    _, dense2, _, cfg2 = auto_models.from_pretrained(out2, task="causal-lm")
    assert cfg2.pipeline_stages == 0
    np.testing.assert_allclose(
        np.asarray(dense2["backbone"]["h_0"]["attention"]["qkv"]["kernel"]),
        np.asarray(bb["h_0"]["attention"]["qkv"]["kernel"]), atol=1e-6)


def test_gpt2_pipelined_decode_raises():
    cfg = _gpt2_cfg(pp=2)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg)
    ids, mask = _inputs(batch=2)
    with pytest.raises(ValueError, match="decode"):
        model.apply({"params": params}, ids, mask, deterministic=True,
                    decode=True, mutable=["cache"])


# --- T5 (encoder-decoder) pipeline ---------------------------------------

def _t5_cfg(pp=0, **kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import T5Config
    base = dict(vocab_size=256, d_model=32, d_kv=8, d_ff=64, num_layers=L,
                num_decoder_layers=L, num_heads=4, dropout_rate=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return T5Config(**base)


def _t5_inputs(batch=8, tgt=8):
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(5, 250, (batch, SEQ)), jnp.int32)
    mask = jnp.ones((batch, SEQ), jnp.int32)
    dec = jnp.asarray(rng.randint(5, 250, (batch, tgt)), jnp.int32)
    dmask = jnp.ones((batch, tgt), jnp.int32)
    return ids, mask, dec, dmask


def _t5_transplant(dense_params, pp_params, gated=False):
    """Dense T5 params → the pipelined layout (what auto.from_pretrained
    does through the checkpoint; done in-memory here)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
        t5_layer_leaves,
        full_stacked_name,
    )
    out = jax.tree.map(lambda x: x, pp_params)
    out["shared"] = dense_params["shared"]
    if "lm_head" in dense_params:
        out["lm_head"] = dense_params["lm_head"]
    for stack, dec in (("encoder", False), ("decoder", True)):
        blocks = {k: v for k, v in dense_params[stack].items()
                  if k.startswith("block_")}
        blk0 = dict(blocks["block_0"])
        blk0["self_attn"] = dict(blk0["self_attn"])
        rel = blk0["self_attn"].pop("rel_bias")
        blocks = dict(blocks, block_0=blk0)
        stacked = stack_layer_params(blocks, L, t5_layer_leaves(dec, gated),
                                     "block_{}", full_stacked_name)
        out[stack] = {
            **{k: jnp.asarray(v) for k, v in stacked.items()},
            "rel_bias": rel,
            "final_ln": dense_params[stack]["final_ln"],
        }
    return out


def test_t5_pipelined_matches_dense_forward():
    """Same weights → identical seq2seq logits: the schedule (with
    cross-attention riders and the stack-level rel bias) is a
    re-ordering of the dense math."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    dense_cfg = _t5_cfg(pp=0)
    dense = T5ForConditionalGeneration(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    pp_cfg = _t5_cfg(pp=2, pipeline_microbatches=4)
    piped = T5ForConditionalGeneration(pp_cfg)
    pp_params = _t5_transplant(dense_params, init_params(piped, pp_cfg))

    ids, mask, dec, dmask = _t5_inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask, dec, dmask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, dec, dmask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=2e-5)


def test_t5_pipelined_gated_untied_matches_dense_forward():
    """The t5-v1.1 shape: gated-gelu FFN (wi_0/wi_1) + untied lm_head."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    kw = dict(feed_forward_proj="gated-gelu", tie_word_embeddings=False)
    dense_cfg = _t5_cfg(pp=0, **kw)
    dense = T5ForConditionalGeneration(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    pp_cfg = _t5_cfg(pp=2, **kw)
    piped = T5ForConditionalGeneration(pp_cfg)
    pp_params = _t5_transplant(dense_params, init_params(piped, pp_cfg),
                               gated=True)

    ids, mask, dec, dmask = _t5_inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask, dec, dmask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, dec, dmask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-4, rtol=1e-3)


def test_t5_pp_mesh_training_matches_single_device(devices8):
    """dp2 x pp2 x tp2 training of the pipelined T5 == single-device
    dense training, loss for loss (seq2seq task through the Trainer)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    tok = WordHashTokenizer(vocab_size=256)
    sources, targets = synthetic_summarization(32, seed=5)
    ds = ArrayDataset.from_seq2seq(tok, sources, targets,
                                   max_source_length=SEQ,
                                   max_target_length=8)

    def run(mesh_cfg, devices, pp):
        mesh = build_mesh(mesh_cfg, devices=devices)
        model_cfg = _t5_cfg(pp=pp, pipeline_microbatches=4)
        model = T5ForConditionalGeneration(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        if pp:
            dense_cfg = _t5_cfg(pp=0)
            dense_params = init_params(
                T5ForConditionalGeneration(dense_cfg), dense_cfg, seed=0)
            params = _t5_transplant(dense_params, params)
        cfg = TrainConfig(task="seq2seq", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          pp=pp or 1)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 2:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1], pp=0)
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8, pp=2)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_t5_hf_checkpoint_roundtrips_through_pipelined(tmp_path):
    """dense export → pipelined load → pipelined export → dense load:
    weights (incl. block 0's rel bias ↔ the stack-level embed) survive
    the full cycle."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    dense_cfg = _t5_cfg()
    dense = T5ForConditionalGeneration(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "t5-dense")
    auto_models.save_pretrained(out, dense_params, "t5", dense_cfg)

    model, params, fam, cfg = auto_models.from_pretrained(
        out, task="seq2seq", pipeline_stages=2, dropout_rate=0.0)
    assert fam == "t5" and cfg.pipeline_stages == 2
    np.testing.assert_allclose(
        np.asarray(params["encoder"]["rel_bias"]["embedding"]),
        np.asarray(dense_params["encoder"]["block_0"]["self_attn"]
                   ["rel_bias"]["embedding"]), atol=1e-6)
    # pipelined logits == dense logits through the checkpoint
    ids, mask, dec, dmask = _t5_inputs(batch=4)
    out_dense = dense.apply({"params": dense_params}, ids, mask, dec, dmask,
                            deterministic=True)
    out_pp = model.apply({"params": params}, ids, mask, dec, dmask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=2e-5)

    out2 = str(tmp_path / "t5-pp-export")
    auto_models.save_pretrained(out2, params, "t5", cfg)
    _, dense2, _, cfg2 = auto_models.from_pretrained(out2, task="seq2seq")
    assert cfg2.pipeline_stages == 0
    np.testing.assert_allclose(
        np.asarray(dense2["decoder"]["block_1"]["cross_attn"]["query"]
                   ["kernel"]),
        np.asarray(dense_params["decoder"]["block_1"]["cross_attn"]["query"]
                   ["kernel"]), atol=1e-6)


def test_t5_pipelined_decode_raises():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    cfg = _t5_cfg(pp=2)
    model = T5ForConditionalGeneration(cfg)
    params = init_params(model, cfg)
    ids, mask, dec, dmask = _t5_inputs(batch=2)
    enc = model.apply({"params": params}, ids, mask,
                      method=model.encode)
    with pytest.raises(ValueError, match="decode"):
        model.apply({"params": params}, dec, enc, mask, dmask, True, True,
                    method=model.decode, mutable=["cache"])


# --- BART/mBART (encoder-decoder) pipeline -------------------------------

def _bart_cfg(pp=0, **kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartConfig,
    )
    base = dict(vocab_size=256, d_model=32, encoder_layers=L,
                decoder_layers=L, encoder_attention_heads=4,
                decoder_attention_heads=4, encoder_ffn_dim=64,
                decoder_ffn_dim=64, max_position_embeddings=64,
                dropout=0.0, attention_dropout=0.0, activation_dropout=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return BartConfig(**base)


def _bart_transplant(dense_params, pp_params):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
        bart_layer_leaves,
        full_stacked_name,
    )
    out = jax.tree.map(lambda x: x, pp_params)
    out["shared"] = dense_params["shared"]
    for stack, dec in (("encoder", False), ("decoder", True)):
        blocks = {k: v for k, v in dense_params[stack].items()
                  if k.startswith("layer_")}
        stacked = stack_layer_params(blocks, L, bart_layer_leaves(dec),
                                     "layer_{}", full_stacked_name)
        keep = {k: v for k, v in dense_params[stack].items()
                if not k.startswith("layer_")}
        out[stack] = {**{k: jnp.asarray(v) for k, v in stacked.items()},
                      **keep}
    return out


@pytest.mark.parametrize("variant", ["bart", "mbart"])
def test_bart_pipelined_matches_dense_forward(variant):
    """Same weights → identical logits for post-LN BART and pre-LN
    mBART (stack final_ln at stack level)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartForConditionalGeneration,
    )

    kw = (dict(normalize_before=True, stack_final_ln=True)
          if variant == "mbart" else {})
    dense_cfg = _bart_cfg(pp=0, **kw)
    dense = BartForConditionalGeneration(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    pp_cfg = _bart_cfg(pp=2, pipeline_microbatches=4, **kw)
    piped = BartForConditionalGeneration(pp_cfg)
    pp_params = _bart_transplant(dense_params, init_params(piped, pp_cfg))

    rng = np.random.RandomState(2)
    ids = jnp.asarray(rng.randint(5, 250, (8, SEQ)), jnp.int32)
    mask = jnp.ones((8, SEQ), jnp.int32)
    dec = jnp.asarray(rng.randint(5, 250, (8, 8)), jnp.int32)
    dmask = jnp.ones((8, 8), jnp.int32)
    out_dense = dense.apply({"params": dense_params}, ids, mask, dec, dmask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, dec, dmask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-4, rtol=1e-3)


def test_bart_hf_checkpoint_roundtrips_through_pipelined(tmp_path):
    """dense export → pipelined load → identical logits → pipelined
    export → dense load with surviving weights."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartForConditionalGeneration,
    )

    dense_cfg = _bart_cfg()
    dense = BartForConditionalGeneration(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "bart-dense")
    auto_models.save_pretrained(out, dense_params, "bart", dense_cfg)

    model, params, fam, cfg = auto_models.from_pretrained(
        out, task="seq2seq", pipeline_stages=2, dropout=0.0,
        attention_dropout=0.0, activation_dropout=0.0)
    assert fam == "bart" and cfg.pipeline_stages == 2
    rng = np.random.RandomState(3)
    ids = jnp.asarray(rng.randint(5, 250, (4, SEQ)), jnp.int32)
    mask = jnp.ones((4, SEQ), jnp.int32)
    dec = jnp.asarray(rng.randint(5, 250, (4, 8)), jnp.int32)
    dmask = jnp.ones((4, 8), jnp.int32)
    out_dense = dense.apply({"params": dense_params}, ids, mask, dec, dmask,
                            deterministic=True)
    out_pp = model.apply({"params": params}, ids, mask, dec, dmask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-4, rtol=1e-3)

    out2 = str(tmp_path / "bart-pp-export")
    auto_models.save_pretrained(out2, params, "bart", cfg)
    _, dense2, _, cfg2 = auto_models.from_pretrained(out2, task="seq2seq")
    assert cfg2.pipeline_stages == 0
    np.testing.assert_allclose(
        np.asarray(dense2["decoder"]["layer_1"]["cross_attn"]["query"]
                   ["kernel"]),
        np.asarray(dense_params["decoder"]["layer_1"]["cross_attn"]["query"]
                   ["kernel"]), atol=1e-6)


def test_bart_pipelined_decode_raises():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartForConditionalGeneration,
    )

    cfg = _bart_cfg(pp=2)
    model = BartForConditionalGeneration(cfg)
    params = init_params(model, cfg)
    rng = np.random.RandomState(4)
    ids = jnp.asarray(rng.randint(5, 250, (2, SEQ)), jnp.int32)
    mask = jnp.ones((2, SEQ), jnp.int32)
    dec = jnp.asarray(rng.randint(5, 250, (2, 4)), jnp.int32)
    enc = model.apply({"params": params}, ids, mask, method=model.encode)
    with pytest.raises(ValueError, match="decode"):
        model.apply({"params": params}, dec, enc, mask, None, True, True,
                    method=model.decode, mutable=["cache"])


def test_t5_pipelined_rejects_ring_attention():
    """pp + sp (ring) is an invalid combo for T5: the pipelined stack
    threads a dense bias the ring path would misread — reject loudly."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5ForConditionalGeneration,
    )

    cfg = _t5_cfg(pp=2, attention_impl="ring")
    model = T5ForConditionalGeneration(cfg)
    with pytest.raises(ValueError, match="ring"):
        init_params(model, cfg)


# --- Llama family (models/pipeline.py::PipelinedLlamaStack) -----------------


def _llama_cfg(pp=0, **kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import LlamaConfig

    base = dict(vocab_size=256, hidden_size=32, num_layers=L, num_heads=4,
                num_kv_heads=2, intermediate_size=64,
                max_position_embeddings=SEQ, pipeline_stages=pp)
    base.update(kw)
    return LlamaConfig(**base)


def _llama_pair(**kw):
    """(dense model+params, pipelined model+params, SAME weights)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaForCausalLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
        llama_layer_leaves,
    )

    dense_cfg = _llama_cfg(pp=0, **kw)
    dense = LlamaForCausalLM(dense_cfg)
    dense_params = init_params(dense, dense_cfg)

    pp_cfg = _llama_cfg(pp=2, pipeline_microbatches=4, **kw)
    piped = LlamaForCausalLM(pp_cfg)
    pp_params = init_params(piped, pp_cfg)
    bb = dense_params["backbone"]
    leaves = llama_layer_leaves(dense_cfg.qkv_bias)
    pp_params["backbone"]["pipelined_layers"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params({k: bb[k] for k in bb if k.startswith("layers_")},
                           L, leaves, "layers_{}"))
    for key in ("embed_tokens", "final_ln"):
        pp_params["backbone"][key] = bb[key]
    if "lm_head" in dense_params:
        pp_params["lm_head"] = dense_params["lm_head"]
    return dense, dense_params, piped, pp_params


def test_llama_pipelined_matches_dense_forward():
    dense, dense_params, piped, pp_params = _llama_pair()
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_llama_qwen2_bias_pipelined_matches_dense_forward():
    """qkv_bias=True (Qwen2) adds bias leaves to the stacked tree."""
    dense, dense_params, piped, pp_params = _llama_pair(qkv_bias=True)
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_llama_pipelined_grads_match_dense():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
        llama_layer_leaves,
    )

    dense, dense_params, piped, pp_params = _llama_pair()
    ids, mask = _inputs()

    def loss_dense(p):
        return jnp.mean(dense.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    def loss_pp(p):
        return jnp.mean(piped.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    g_dense = jax.grad(loss_dense)(dense_params)
    g_pp = jax.grad(loss_pp)(pp_params)
    leaves = llama_layer_leaves(False)
    g_layers = unstack_layer_params(
        jax.tree.map(np.asarray, g_pp["backbone"]["pipelined_layers"]), L,
        leaves, "layers_{}")
    for i in range(L):
        for sub, leaf in (("self_attn", "q_proj"), ("mlp", "down_proj")):
            np.testing.assert_allclose(
                g_layers[f"layers_{i}"][sub][leaf]["kernel"],
                np.asarray(g_dense["backbone"][f"layers_{i}"][sub][leaf]["kernel"]),
                atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_pp["backbone"]["embed_tokens"]["embedding"]),
        np.asarray(g_dense["backbone"]["embed_tokens"]["embedding"]),
        atol=2e-4)


def test_llama_hf_checkpoint_roundtrips_through_pipelined(tmp_path):
    """dense export → pipelined load (stacked weights match) → pipelined
    export → dense load (weights survive the full cycle)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaForCausalLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
        llama_layer_leaves,
    )

    dense_cfg = _llama_cfg()
    dense = LlamaForCausalLM(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "llama-dense")
    auto_models.save_pretrained(out, dense_params, "llama", dense_cfg)

    model, params, fam, cfg = auto_models.from_pretrained(
        out, task="causal-lm", pipeline_stages=2)
    assert fam == "llama" and cfg.pipeline_stages == 2
    bb = dense_params["backbone"]
    leaves = llama_layer_leaves(False)
    stacked = stack_layer_params(
        {k: bb[k] for k in bb if k.startswith("layers_")}, L, leaves,
        "layers_{}")
    for name, arr in stacked.items():
        np.testing.assert_allclose(
            np.asarray(params["backbone"]["pipelined_layers"][name]), arr,
            atol=1e-6)

    out2 = str(tmp_path / "llama-pp-export")
    auto_models.save_pretrained(out2, params, "llama", cfg)
    _, dense2, _, cfg2 = auto_models.from_pretrained(out2, task="causal-lm")
    assert cfg2.pipeline_stages == 0
    np.testing.assert_allclose(
        np.asarray(dense2["backbone"]["layers_0"]["self_attn"]["q_proj"]["kernel"]),
        np.asarray(bb["layers_0"]["self_attn"]["q_proj"]["kernel"]), atol=1e-6)


def test_llama_pipelined_invalid_combos_raise():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaForCausalLM,
    )

    for kw, msg in ((dict(sliding_window=8), "sliding_window"),
                    (dict(attention_impl="ring"), "ring"),
                    (dict(weight_quant="int8"), "weight_quant")):
        cfg = _llama_cfg(pp=2, **kw)
        model = LlamaForCausalLM(cfg)
        with pytest.raises(ValueError, match=msg):
            init_params(model, cfg)


def test_llama_pipelined_decode_raises():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaForCausalLM,
    )

    cfg = _llama_cfg(pp=2)
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg)
    ids, mask = _inputs(batch=2)
    with pytest.raises(ValueError, match="decode"):
        model.apply({"params": params}, ids, mask, decode=True,
                    mutable=["cache"])


def test_llama_pp_mesh_training_matches_single_device(devices8):
    """dp2×pp2×tp2 causal-lm training = single-device pipelined training."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaForCausalLM,
    )

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=3)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry")
        model_cfg = _llama_cfg(pp=2)
        model = LlamaForCausalLM(model_cfg)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)
