"""Pipeline-parallelism tests (models/pipeline.py, the ``pipe`` mesh
axis). Contract: the GPipe schedule is bit-compatible with the dense
Encoder (same math, different execution order), composes with dp/tp on
a real mesh, and round-trips HF checkpoints through the stacked layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
    stack_layer_params,
    unstack_layer_params,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    param_shardings,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 16
L = 4


def _cfg(pp=0, **kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=L, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                hidden_dropout=0.0, attention_dropout=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return EncoderConfig(**base)


def _inputs(batch=8):
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(5, 250, (batch, SEQ)), jnp.int32)
    mask = jnp.ones((batch, SEQ), jnp.int32)
    return ids, mask


def test_pipelined_matches_dense_forward():
    """Same weights (stacked from the dense model) → identical logits.
    The schedule is a re-ordering of the same math, so tolerance is
    float-roundoff only."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)

    pp_cfg = _cfg(pp=2)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    pp_params = jax.tree.map(lambda x: x, pp_params)  # mutable copy
    pp_params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        pp_params["backbone"][key] = dense_params["backbone"][key]
    pp_params["classifier"] = dense_params["classifier"]

    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_pipelined_grads_match_dense():
    """Backward through scan/roll produces the same gradients as the
    dense stack (mapped back through unstack)."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    pp_cfg = _cfg(pp=2, pipeline_microbatches=4)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    pp_params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        pp_params["backbone"][key] = dense_params["backbone"][key]
    pp_params["classifier"] = dense_params["classifier"]

    ids, mask = _inputs()

    def loss_dense(p):
        return jnp.sum(dense.apply({"params": p}, ids, mask,
                                   deterministic=True) ** 2)

    def loss_pp(p):
        return jnp.sum(piped.apply({"params": p}, ids, mask,
                                   deterministic=True) ** 2)

    g_dense = jax.grad(loss_dense)(dense_params)
    g_pp = jax.grad(loss_pp)(pp_params)
    g_pp_enc = unstack_layer_params(
        jax.tree.map(np.asarray, g_pp["backbone"]["pipelined_encoder"]), L)
    for i in range(L):
        np.testing.assert_allclose(
            g_pp_enc[f"layer_{i}"]["attention"]["query"]["kernel"],
            np.asarray(g_dense["backbone"]["encoder"][f"layer_{i}"]
                       ["attention"]["query"]["kernel"]),
            atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_pp["classifier"]["kernel"]),
        np.asarray(g_dense["classifier"]["kernel"]), atol=2e-4)


def test_stack_unstack_roundtrip():
    cfg = _cfg()
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    enc = params["backbone"]["encoder"]
    back = unstack_layer_params(stack_layer_params(enc, L), L)
    for i in range(L):
        np.testing.assert_array_equal(
            back[f"layer_{i}"]["ffn"]["intermediate"]["kernel"],
            np.asarray(enc[f"layer_{i}"]["ffn"]["intermediate"]["kernel"]))


def test_pp_mesh_training_matches_single_device(devices8):
    """dp2×pp2×tp2 training = single-device pipelined training: the pipe
    axis shards stages but must not change the update."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(32, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          rng_impl="threefry")
        model_cfg = _cfg(pp=2)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_pipelined_params_sharded_over_pipe(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, pp=2, tp=2), devices=devices8)
    model_cfg = _cfg(pp=2)
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    sh = param_shardings(params, mesh)
    enc = sh["backbone"]["pipelined_encoder"]
    assert enc["query_kernel"].spec == P("pipe", None, "tensor")
    assert enc["ffn_out_kernel"].spec == P("pipe", "tensor")
    assert enc["attention_ln_scale"].spec == P("pipe")


def test_hf_checkpoint_loads_into_pipelined_model(tmp_path):
    """Export a dense model, reload with pipeline_stages=2: forward must
    match the dense original (weights stacked on load)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    dense_cfg = _cfg()
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "dense")
    auto_models.save_pretrained(out, dense_params, "bert", dense_cfg)

    model, params, _, cfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2, pipeline_stages=2,
        hidden_dropout=0.0, attention_dropout=0.0)
    assert cfg.pipeline_stages == 2
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = model.apply({"params": params}, ids, mask, deterministic=True)
    # classifier head is freshly initialized on load, so compare the
    # backbone by re-using the dense head on the pipelined trunk: logits
    # differ, pooled trunk must not — compare via the exported encoder
    np.testing.assert_allclose(
        np.asarray(out_pp).shape, np.asarray(out_dense).shape)
    # strong check: stacked weights equal the dense ones
    stacked = stack_layer_params(dense_params["backbone"]["encoder"], L)
    for name, arr in stacked.items():
        np.testing.assert_allclose(
            np.asarray(params["backbone"]["pipelined_encoder"][name]), arr,
            atol=1e-6)


def test_pipelined_export_roundtrip(tmp_path):
    """save_pretrained of a pipelined model writes per-layer HF layout
    loadable as a dense model with identical weights."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    pp_cfg = _cfg(pp=2)
    piped = BertForSequenceClassification(pp_cfg, num_labels=2)
    pp_params = init_params(piped, pp_cfg)
    out = str(tmp_path / "pp-export")
    auto_models.save_pretrained(out, pp_params, "bert", pp_cfg)

    _, dense_params, _, dense_cfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2)
    assert dense_cfg.pipeline_stages == 0
    stacked = pp_params["backbone"]["pipelined_encoder"]
    restacked = stack_layer_params(dense_params["backbone"]["encoder"], L)
    for name in restacked:
        np.testing.assert_allclose(restacked[name], np.asarray(stacked[name]),
                                   atol=1e-6)


def test_non_dividing_microbatches_degrade_to_gcd():
    """batch 8 with pipeline_microbatches=3 → effective M=1; outputs are
    M-invariant so results still match the dense model."""
    dense_cfg = _cfg(pp=0)
    dense = BertForSequenceClassification(dense_cfg, num_labels=2)
    dense_params = init_params(dense, dense_cfg)
    cfg = _cfg(pp=2, pipeline_microbatches=3)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    params["backbone"]["pipelined_encoder"] = jax.tree.map(
        jnp.asarray, stack_layer_params(dense_params["backbone"]["encoder"], L))
    for key in ("embeddings", "pooler"):
        params["backbone"][key] = dense_params["backbone"][key]
    params["classifier"] = dense_params["classifier"]
    ids, mask = _inputs(batch=8)
    out_pp = model.apply({"params": params}, ids, mask, deterministic=True)
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_dropout_runs_under_pipeline():
    """Non-deterministic path (per-tick/stage/layer folded keys) runs and
    produces different outputs across dropout keys."""
    cfg = _cfg(pp=2, hidden_dropout=0.5)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    ids, mask = _inputs()
    outs = [model.apply({"params": params}, ids, mask, deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(k)})
            for k in (0, 1)]
    assert not np.allclose(np.asarray(outs[0]), np.asarray(outs[1]))


# --- GPT-2 (decoder-only family) under the same schedule ---------------------

from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (  # noqa: E402
    Gpt2Config,
    Gpt2LMHeadModel,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (  # noqa: E402
    GPT2_LAYER_LEAVES,
)


def _gpt2_cfg(pp=0, **kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=L, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                hidden_dropout=0.0, embd_dropout=0.0, attention_dropout=0.0,
                pipeline_stages=pp)
    base.update(kw)
    return Gpt2Config(**base)


def _gpt2_pair():
    """(dense model+params, pipelined model+params with the SAME weights)."""
    dense_cfg = _gpt2_cfg(pp=0)
    dense = Gpt2LMHeadModel(dense_cfg)
    dense_params = init_params(dense, dense_cfg)

    pp_cfg = _gpt2_cfg(pp=2, pipeline_microbatches=4)
    piped = Gpt2LMHeadModel(pp_cfg)
    pp_params = init_params(piped, pp_cfg)
    bb = dense_params["backbone"]
    pp_params["backbone"]["pipelined_h"] = jax.tree.map(
        jnp.asarray,
        stack_layer_params({k: bb[k] for k in bb if k.startswith("h_")}, L,
                           GPT2_LAYER_LEAVES, "h_{}"))
    for key in ("wte", "wpe", "ln_f"):
        pp_params["backbone"][key] = bb[key]
    return dense, dense_params, piped, pp_params


def test_gpt2_pipelined_matches_dense_forward():
    dense, dense_params, piped, pp_params = _gpt2_pair()
    ids, mask = _inputs()
    out_dense = dense.apply({"params": dense_params}, ids, mask,
                            deterministic=True)
    out_pp = piped.apply({"params": pp_params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_dense),
                               atol=1e-5)


def test_gpt2_pipelined_grads_match_dense():
    dense, dense_params, piped, pp_params = _gpt2_pair()
    ids, mask = _inputs()

    def loss_dense(p):
        return jnp.mean(dense.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    def loss_pp(p):
        return jnp.mean(piped.apply({"params": p}, ids, mask,
                                    deterministic=True) ** 2)

    g_dense = jax.grad(loss_dense)(dense_params)
    g_pp = jax.grad(loss_pp)(pp_params)
    g_layers = unstack_layer_params(
        jax.tree.map(np.asarray, g_pp["backbone"]["pipelined_h"]), L,
        GPT2_LAYER_LEAVES, "h_{}")
    for i in range(L):
        np.testing.assert_allclose(
            g_layers[f"h_{i}"]["attention"]["qkv"]["kernel"],
            np.asarray(g_dense["backbone"][f"h_{i}"]["attention"]["qkv"]["kernel"]),
            atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(g_pp["backbone"]["wte"]["embedding"]),
        np.asarray(g_dense["backbone"]["wte"]["embedding"]), atol=2e-4)


def test_gpt2_pp_mesh_training_matches_single_device(devices8):
    """dp2×pp2×tp2 causal-lm training = single-device pipelined training."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=3)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry")
        model_cfg = _gpt2_cfg(pp=2)
        model = Gpt2LMHeadModel(model_cfg)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, pp=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_gpt2_pipelined_params_sharded_over_pipe(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, pp=2, tp=2), devices=devices8)
    model_cfg = _gpt2_cfg(pp=2)
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg)
    sh = param_shardings(params, mesh)
    stacked = sh["backbone"]["pipelined_h"]
    assert stacked["qkv_kernel"].spec == P("pipe", None, "tensor")
    assert stacked["fc_out_kernel"].spec == P("pipe", "tensor")
    assert stacked["ln_1_scale"].spec == P("pipe")


def test_gpt2_hf_checkpoint_roundtrips_through_pipelined(tmp_path):
    """dense export → pipelined load (stacked weights match) → pipelined
    export → dense load (weights survive the full cycle)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    dense_cfg = _gpt2_cfg()
    dense = Gpt2LMHeadModel(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    out = str(tmp_path / "gpt2-dense")
    auto_models.save_pretrained(out, dense_params, "gpt2", dense_cfg)

    model, params, fam, cfg = auto_models.from_pretrained(
        out, task="causal-lm", pipeline_stages=2,
        hidden_dropout=0.0, embd_dropout=0.0, attention_dropout=0.0)
    assert fam == "gpt2" and cfg.pipeline_stages == 2
    bb = dense_params["backbone"]
    stacked = stack_layer_params({k: bb[k] for k in bb if k.startswith("h_")},
                                 L, GPT2_LAYER_LEAVES, "h_{}")
    for name, arr in stacked.items():
        np.testing.assert_allclose(
            np.asarray(params["backbone"]["pipelined_h"][name]), arr,
            atol=1e-6)

    out2 = str(tmp_path / "gpt2-pp-export")
    auto_models.save_pretrained(out2, params, "gpt2", cfg)
    _, dense2, _, cfg2 = auto_models.from_pretrained(out2, task="causal-lm")
    assert cfg2.pipeline_stages == 0
    np.testing.assert_allclose(
        np.asarray(dense2["backbone"]["h_0"]["attention"]["qkv"]["kernel"]),
        np.asarray(bb["h_0"]["attention"]["qkv"]["kernel"]), atol=1e-6)


def test_gpt2_pipelined_decode_raises():
    cfg = _gpt2_cfg(pp=2)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg)
    ids, mask = _inputs(batch=2)
    with pytest.raises(ValueError, match="decode"):
        model.apply({"params": params}, ids, mask, deterministic=True,
                    decode=True, mutable=["cache"])
