"""DeBERTa-v2/v3 family tests: HF torch numerics parity for the
disentangled-attention stack across its configuration space (v3-style
shared-key log buckets, v2-style separate position projections + conv,
c2p-only), head coverage, export round-trip, and trainer integration."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (  # noqa: E402
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (  # noqa: E402
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer  # noqa: E402

TOL = 3e-4


def _hf_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64, type_vocab_size=0,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                pooler_dropout=0.0, relative_attention=True,
                position_buckets=16, norm_rel_ebd="layer_norm",
                share_att_key=True, pos_att_type=["c2p", "p2c"],
                pad_token_id=0)
    base.update(kw)
    return transformers.DebertaV2Config(**base)


def _inputs(batch=3, seq=12, vocab=128, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(4, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    mask[1, 8:] = 0
    ids[1, 8:] = 0
    return ids, mask


def _parity(hf_model, d, task, extra_tol=1.0):
    model, params, family, cfg = auto_models.from_pretrained(
        d, task=task, num_labels=2)
    assert family == "deberta-v2"
    ids, mask = _inputs()
    with torch.no_grad():
        t_out = hf_model(input_ids=torch.tensor(ids),
                         attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    if task == "qa":
        for t, j in [(t_out.start_logits, j_out[0]), (t_out.end_logits, j_out[1])]:
            # padded positions diverge (HF leaves them unmasked garbage);
            # compare the real ones
            np.testing.assert_allclose(np.asarray(j)[mask > 0],
                                       t.numpy()[mask > 0],
                                       atol=TOL * extra_tol, rtol=1e-3)
    else:
        np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                                   atol=TOL * extra_tol, rtol=1e-3)
    return model, params, cfg


def test_deberta_v3_style_seq_cls_parity(tmp_path):
    """v3 recipe: shared att key, log buckets, rel-embedding LayerNorm."""
    torch.manual_seed(0)
    m = transformers.DebertaV2ForSequenceClassification(_hf_cfg()).eval()
    d = str(tmp_path / "v3")
    m.save_pretrained(d)
    _parity(m, d, "seq-cls")


def test_deberta_v2_style_separate_pos_proj_parity(tmp_path):
    """v2 recipe: separate pos_key/pos_query projections, no buckets
    (linear relative positions up to max_relative_positions)."""
    torch.manual_seed(1)
    m = transformers.DebertaV2ForSequenceClassification(
        _hf_cfg(share_att_key=False, position_buckets=-1,
                max_relative_positions=16, norm_rel_ebd="none")).eval()
    d = str(tmp_path / "v2")
    m.save_pretrained(d)
    _parity(m, d, "seq-cls")


def test_deberta_conv_layer_parity(tmp_path):
    """deberta-v2-xlarge recipe: ConvLayer merged after layer 0."""
    torch.manual_seed(2)
    m = transformers.DebertaV2ForSequenceClassification(
        _hf_cfg(conv_kernel_size=3, conv_act="tanh")).eval()
    d = str(tmp_path / "conv")
    m.save_pretrained(d)
    _parity(m, d, "seq-cls")


def test_deberta_c2p_only_parity(tmp_path):
    torch.manual_seed(3)
    m = transformers.DebertaV2ForSequenceClassification(
        _hf_cfg(pos_att_type=["c2p"])).eval()
    d = str(tmp_path / "c2p")
    m.save_pretrained(d)
    _parity(m, d, "seq-cls")


def test_deberta_embedding_size_and_token_types_parity(tmp_path):
    """Factorized embedding (embed_proj) + token-type embeddings."""
    torch.manual_seed(4)
    m = transformers.DebertaV2ForSequenceClassification(
        _hf_cfg(embedding_size=16, type_vocab_size=2)).eval()
    d = str(tmp_path / "emb")
    m.save_pretrained(d)
    _parity(m, d, "seq-cls")


def test_deberta_token_cls_and_qa_parity(tmp_path):
    torch.manual_seed(5)
    cfg = _hf_cfg(num_labels=2)
    mt = transformers.DebertaV2ForTokenClassification(cfg).eval()
    d1 = str(tmp_path / "tok")
    mt.save_pretrained(d1)
    _parity(mt, d1, "token-cls")
    mq = transformers.DebertaV2ForQuestionAnswering(cfg).eval()
    d2 = str(tmp_path / "qa")
    mq.save_pretrained(d2)
    _parity(mq, d2, "qa")


def test_deberta_hub_style_string_pos_att_type(tmp_path):
    """Raw hub config.json stores pos_att_type as the string "c2p|p2c";
    it must parse into the tuple, not char-split (which would silently
    disable disentangled attention)."""
    import json

    torch.manual_seed(7)
    m = transformers.DebertaV2ForSequenceClassification(_hf_cfg()).eval()
    d = str(tmp_path / "hub")
    m.save_pretrained(d)
    cfg = json.load(open(f"{d}/config.json"))
    cfg["pos_att_type"] = "c2p|p2c"
    json.dump(cfg, open(f"{d}/config.json", "w"))
    model, params, _ = _parity(m, d, "seq-cls")
    assert model.config.pos_att_type == ("c2p", "p2c")


def test_deberta_export_roundtrip(tmp_path):
    """Our export reloads in HF torch with identical logits."""
    torch.manual_seed(6)
    m = transformers.DebertaV2ForSequenceClassification(_hf_cfg()).eval()
    d = str(tmp_path / "src")
    m.save_pretrained(d)
    model, params, fam, cfg = auto_models.from_pretrained(
        d, task="seq-cls", num_labels=2)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, fam, cfg)
    m2 = transformers.DebertaV2ForSequenceClassification.from_pretrained(out).eval()
    ids, mask = _inputs()
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
        b = m2(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


def test_deberta_training_learns(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.deberta import (
        DebertaV2Config,
        DebertaV2ForSequenceClassification,
    )

    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=16)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    model_cfg = DebertaV2Config(
        vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=16,
        position_buckets=8, hidden_dropout=0.0, attention_dropout=0.0)
    model = DebertaV2ForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    cfg = TrainConfig(dtype="float32", learning_rate=1e-2,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=6)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.8


def test_deberta_mlm_parity(tmp_path):
    """Legacy DebertaV2ForMaskedLM (cls.predictions head, tied decoder);
    weights perturbed so dropped params can't hide behind fresh init."""
    torch.manual_seed(8)
    m = transformers.DebertaV2ForMaskedLM(_hf_cfg()).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "mlm")
    m.save_pretrained(d)
    model, params, family, cfg = auto_models.from_pretrained(d, task="mlm")
    ids, mask = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out)[mask > 0],
                               t_out.logits.numpy()[mask > 0],
                               atol=TOL, rtol=1e-3)


def test_deberta_mlm_non_legacy_rejected(tmp_path):
    """HF legacy=false MLM checkpoints are rejected loudly: HF's own
    tie_weights clobbers lm_head.dense with the embedding matrix (its
    forward crashes in transformers 4.57), so a silent partial load
    would leave a random head."""
    torch.manual_seed(10)
    m = transformers.DebertaV2ForMaskedLM(_hf_cfg(legacy=False)).eval()
    d = str(tmp_path / "mlm-nl")
    m.save_pretrained(d)
    with pytest.raises(ValueError, match="non-legacy"):
        auto_models.from_pretrained(d, task="mlm")
