"""Speculative decoding (models/generate.py::generate_speculative).

Contract: the output is EXACTLY the target model's greedy continuation
(generate_causal at temperature 0) for every draft model, every
speculate_k, and every acceptance pattern — the draft changes speed,
never tokens. Verified across the Llama and GPT-2 cache conventions,
with an adversarial draft (random weights, near-zero acceptance), a
perfect draft (the target itself, full acceptance), and EOS mid-window.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate_causal,
    generate_speculative,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
    Gpt2Config,
    Gpt2LMHeadModel,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)


def _llama(num_layers, seed):
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=num_layers,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    return model, init_params(model, cfg, seed=seed)


def _gpt2(num_layers, seed):
    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=num_layers,
                     num_heads=4, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0)
    model = Gpt2LMHeadModel(cfg)
    return model, init_params(model, cfg, seed=seed)


@pytest.mark.parametrize("family", ["llama", "gpt2"])
@pytest.mark.parametrize("k", [1, 3, 5])
def test_speculative_matches_greedy(family, k):
    build = _llama if family == "llama" else _gpt2
    target, t_params = build(3, seed=0)
    draft, d_params = build(1, seed=1)

    rng = np.random.RandomState(0)
    ids = rng.randint(3, 128, (1, 7))
    want = np.asarray(generate_causal(target, t_params, ids,
                                      max_new_tokens=16))
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          ids, max_new_tokens=16,
                                          speculate_k=k))
    np.testing.assert_array_equal(got, want)


def test_speculative_perfect_draft_full_acceptance():
    """Draft == target: every window fully accepted, still exact."""
    target, t_params = _llama(2, seed=0)
    rng = np.random.RandomState(1)
    ids = rng.randint(3, 128, (1, 5))
    want = np.asarray(generate_causal(target, t_params, ids,
                                      max_new_tokens=12))
    got = np.asarray(generate_speculative(target, t_params, target, t_params,
                                          ids, max_new_tokens=12,
                                          speculate_k=4))
    np.testing.assert_array_equal(got, want)


def test_speculative_eos_mid_window_pads_after():
    """A target whose greedy continuation hits EOS: speculative output
    must pad after it exactly like generate_causal (EOS can land
    mid-verify-window, exercising the emit masking)."""
    target, t_params = _llama(2, seed=3)
    draft, d_params = _llama(1, seed=4)
    # scan seeds until the greedy continuation actually contains EOS (2)
    found = None
    for seed in range(40):
        ids = np.random.RandomState(seed).randint(3, 128, (1, 6))
        want = np.asarray(generate_causal(target, t_params, ids,
                                          max_new_tokens=12))
        if (want == 2).any():
            found = (ids, want)
            break
    if found is None:
        pytest.skip("no EOS-producing prompt found for this init")
    ids, want = found
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          ids, max_new_tokens=12,
                                          speculate_k=3))
    np.testing.assert_array_equal(got, want)


def test_speculative_batched_rows_advance_independently():
    """Batched rows with DIFFERENT prompts (different acceptance
    patterns and EOS times) each match their own greedy continuation —
    the per-row cache-index machinery."""
    target, t_params = _llama(3, seed=0)
    draft, d_params = _llama(1, seed=1)
    rng = np.random.RandomState(11)
    ids = rng.randint(3, 128, (4, 7))
    want = np.asarray(generate_causal(target, t_params, ids,
                                      max_new_tokens=14))
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          ids, max_new_tokens=14,
                                          speculate_k=3))
    np.testing.assert_array_equal(got, want)
    # and with per-row right-padding (different real lengths per row)
    mask = np.ones((4, 7), np.int64)
    mask[0, 5:] = 0
    mask[2, 3:] = 0
    ids_masked = ids * mask
    want = np.asarray(generate_causal(target, t_params, ids_masked, mask,
                                      max_new_tokens=14))
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          ids_masked, mask,
                                          max_new_tokens=14,
                                          speculate_k=3))
    np.testing.assert_array_equal(got, want)


def test_speculative_rejects_bad_inputs():
    target, t_params = _llama(2, seed=0)
    draft, d_params = _llama(1, seed=1)
    with pytest.raises(ValueError, match="speculate_k"):
        generate_speculative(target, t_params, draft, d_params,
                             jnp.ones((1, 4), jnp.int32), speculate_k=0)

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=1,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128)
    other = LlamaForCausalLM(cfg)
    o_params = init_params(other, cfg, seed=2)
    with pytest.raises(ValueError, match="vocabulary"):
        generate_speculative(target, t_params, other, o_params,
                             jnp.ones((1, 4), jnp.int32))


def test_speculative_right_padded_prompt_matches_unpadded():
    """Bucketed (right-padded) prompts produce the same tokens as the
    exact-length prompt — the mask/positions plumbing that lets callers
    compile once per width bucket."""
    target, t_params = _llama(3, seed=0)
    draft, d_params = _llama(1, seed=1)
    rng = np.random.RandomState(5)
    ids = rng.randint(3, 128, (1, 7))
    want = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                           ids, max_new_tokens=12,
                                           speculate_k=3))
    padded = np.zeros((1, 16), np.int64)
    padded[:, :7] = ids
    mask = np.zeros((1, 16), np.int64)
    mask[:, :7] = 1
    got = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                          padded, mask, max_new_tokens=12,
                                          speculate_k=3))
    np.testing.assert_array_equal(got, want)
    # and the padded run still equals plain greedy on the padded prompt
    ref = np.asarray(generate_causal(target, t_params, padded, mask,
                                     max_new_tokens=12))
    np.testing.assert_array_equal(got, ref)


def test_speculative_left_padded_rejected():
    target, t_params = _llama(2, seed=0)
    draft, d_params = _llama(1, seed=1)
    ids = np.ones((1, 8), np.int64) * 5
    mask = np.concatenate([np.zeros((1, 3), np.int64),
                           np.ones((1, 5), np.int64)], axis=1)
    with pytest.raises(ValueError, match="RIGHT-padded"):
        generate_speculative(target, t_params, draft, d_params, ids, mask)


def test_self_draft_matches_greedy():
    """Layer-skip self-speculation: the draft is the target's own first
    N layers — no second checkpoint — and the output is still exactly
    the target's greedy continuation (for both param layouts)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        self_draft,
    )

    for build in (_llama, _gpt2):
        target, t_params = build(3, seed=0)
        draft, d_params = self_draft(target, t_params, 1)
        assert draft.config.num_layers == 1
        rng = np.random.RandomState(7)
        ids = rng.randint(3, 128, (1, 6))
        want = np.asarray(generate_causal(target, t_params, ids,
                                          max_new_tokens=10))
        got = np.asarray(generate_speculative(target, t_params, draft,
                                              d_params, ids,
                                              max_new_tokens=10,
                                              speculate_k=3))
        np.testing.assert_array_equal(got, want)


def test_self_draft_rejects_bad_layer_counts():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        self_draft,
    )

    target, t_params = _llama(3, seed=0)
    with pytest.raises(ValueError, match="num_layers"):
        self_draft(target, t_params, 0)
    with pytest.raises(ValueError, match="num_layers"):
        self_draft(target, t_params, 3)


def test_speculative_stats_reporting():
    """return_stats exposes iteration count and accepted-per-window —
    a perfect draft accepts the full window every time."""
    target, t_params = _llama(2, seed=0)
    rng = np.random.RandomState(3)
    ids = rng.randint(3, 128, (1, 5))
    tokens, stats = generate_speculative(
        target, t_params, target, t_params, ids, max_new_tokens=12,
        speculate_k=3, return_stats=True)
    want = np.asarray(generate_causal(target, t_params, ids,
                                      max_new_tokens=12))
    np.testing.assert_array_equal(np.asarray(tokens), want)
    assert stats["window_ceiling"] == 4
    assert 1.0 <= stats["accepted_per_window"] <= 4.0
    # perfect draft: every window fully accepted unless EOS cut it
    # short — the metric uses RAW window yields (the final window may
    # overshoot max_new_tokens), so it sits exactly at the ceiling
    if not (want == 2).any():
        assert stats["iterations"] == 3       # ceil((12-1)/4)
        assert stats["accepted_per_window"] == 4.0


def test_rejection_acceptance_marginal_is_target_distribution():
    """The Leviathan acceptance theorem, checked on OUR implementation:
    with drafts sampled from q and (accept → draft | reject → residual)
    from _speculative_accept, the emitted first token's marginal equals
    the target p exactly. 200k Monte-Carlo trials on an 8-token vocab
    pin it to ~0.01 total variation."""
    import jax
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        _speculative_accept,
    )

    rng = np.random.RandomState(0)
    p_np = rng.dirichlet(np.ones(8), size=2).astype(np.float32)  # [k+1=2, V]
    q_np = rng.dirichlet(np.ones(8), size=1).astype(np.float32)  # [k=1, V]
    p, q = jnp.asarray(p_np), jnp.asarray(q_np)

    def trial(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q[0]))[None]
        n_acc, nxt = _speculative_accept(p, q, d.astype(jnp.int32), ka)
        return jnp.where(n_acc > 0, d[0], nxt)

    keys = jax.random.split(jax.random.PRNGKey(42), 200_000)
    emitted = np.asarray(jax.jit(jax.vmap(trial))(keys))
    counts = np.bincount(emitted, minlength=8) / len(emitted)
    tv = 0.5 * np.abs(counts - p_np[0]).sum()
    assert tv < 0.012, f"total variation {tv:.4f} vs target"
    # and the SECOND position (bonus when accepted): conditional on
    # acceptance the extra token must follow p[1]
    def trial2(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q[0]))[None]
        n_acc, nxt = _speculative_accept(p, q, d.astype(jnp.int32), ka)
        return jnp.where(n_acc == 1, nxt, -1)

    bonus = np.asarray(jax.jit(jax.vmap(trial2))(keys))
    bonus = bonus[bonus >= 0]
    counts2 = np.bincount(bonus, minlength=8) / len(bonus)
    tv2 = 0.5 * np.abs(counts2 - p_np[1]).sum()
    assert tv2 < 0.015, f"bonus total variation {tv2:.4f}"


def test_sampled_speculative_end_to_end():
    """temperature > 0: deterministic per seed, different across seeds,
    in-vocab tokens, pads after EOS — the end-to-end plumbing of the
    rejection-sampling mode (distribution exactness is pinned by the
    marginal test above)."""
    target, t_params = _llama(3, seed=0)
    draft, d_params = _llama(1, seed=1)
    rng = np.random.RandomState(0)
    ids = rng.randint(3, 128, (2, 6))
    a = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=12,
                                        speculate_k=3, temperature=0.8,
                                        seed=7))
    b = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=12,
                                        speculate_k=3, temperature=0.8,
                                        seed=7))
    np.testing.assert_array_equal(a, b)        # deterministic per seed
    c = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=12,
                                        speculate_k=3, temperature=0.8,
                                        seed=8))
    assert not np.array_equal(a, c)            # seed actually matters
    assert (a >= 0).all() and (a < 128).all()
    for row in a:                              # pads after EOS
        eos = np.where(row == 2)[0]
        if len(eos):
            assert (row[eos[0] + 1:] == 0).all()


def test_sampled_speculative_with_warpers():
    """top-k/top-p warping applies to BOTH p and q (the theorem holds
    for any warped target): deterministic per seed, valid tokens, and
    at top_k >= vocab it reduces to plain temperature sampling with the
    same rng stream (identical output)."""
    target, t_params = _llama(2, seed=0)
    draft, d_params = _llama(1, seed=1)
    ids = np.random.RandomState(1).randint(3, 128, (1, 6))
    a = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=10,
                                        speculate_k=3, temperature=0.7,
                                        top_k=5, seed=3))
    b = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=10,
                                        speculate_k=3, temperature=0.7,
                                        top_k=5, seed=3))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < 128).all()
    # top_k = vocab is a no-op filter: same tokens as unfiltered
    c = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=10,
                                        speculate_k=3, temperature=0.7,
                                        top_k=128, seed=3))
    d = np.asarray(generate_speculative(target, t_params, draft, d_params,
                                        ids, max_new_tokens=10,
                                        speculate_k=3, temperature=0.7,
                                        seed=3))
    np.testing.assert_array_equal(c, d)
    with pytest.raises(ValueError, match="temperature"):
        generate_speculative(target, t_params, draft, d_params, ids,
                             top_k=5)
