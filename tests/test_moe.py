"""MoE + expert parallelism tests (models/moe.py, the ``expert`` mesh
axis). Beyond-parity capability — the reference has no MoE (SURVEY.md §2
parallelism inventory), so the contract here is internal consistency:
routing conservation, ep-sharded == unsharded numerics, aux-loss wiring,
and expert-sharded checkpoint/divergence behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
    is_moe_layer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.moe import (
    MoeFeedForward,
    expert_capacity,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    param_shardings,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 16


def _moe_cfg(**kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                num_experts=4, expert_top_k=2, moe_every=2)
    base.update(kw)
    return EncoderConfig(**base)


def test_moe_layer_placement():
    cfg = _moe_cfg(num_layers=4)
    assert [is_moe_layer(cfg, i) for i in range(4)] == [False, True, False, True]
    dense = _moe_cfg(num_experts=0)
    assert not any(is_moe_layer(dense, i) for i in range(4))


def test_capacity_static_and_padded():
    cfg = _moe_cfg()
    c = expert_capacity(cfg, 512)
    # ceil(1.25 * 2 * 512 / 4) = 320, already a multiple of 4
    assert c == 320
    assert expert_capacity(cfg, 8) >= 4 and expert_capacity(cfg, 8) % 4 == 0


def test_moe_forward_and_routing_conservation():
    """With generous capacity no token is dropped: the combine weights
    for every token sum to exactly 1 (normalized top-k gates), so the
    MoE output is a convex combination of expert outputs."""
    cfg = _moe_cfg(expert_capacity_factor=4.0)
    layer = MoeFeedForward(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    y, state = layer.apply({"params": params}, x, mutable=["losses"])
    assert y.shape == x.shape
    assert np.all(np.isfinite(jax.device_get(y)))
    (aux,) = jax.tree.leaves(state["losses"])
    # Switch aux loss is >= coef (E * sum f_e P_e >= 1 by Cauchy-Schwarz)
    assert float(aux) >= cfg.router_aux_coef * 0.99


def test_moe_tiny_capacity_drops_gracefully():
    """Capacity 4 with 16 tokens × top-2: most assignments drop; output
    must stay finite and dropped tokens contribute zero (residual rides
    through in the encoder layer)."""
    cfg = _moe_cfg(expert_capacity_factor=0.1)
    layer = MoeFeedForward(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["losses"])
    assert np.all(np.isfinite(jax.device_get(y)))


def _train_losses(mesh_cfg, devices, n_steps=4):
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(32, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    mesh = build_mesh(mesh_cfg, devices=devices)
    cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry")
    model_cfg = _moe_cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
    losses = []
    for step, batch in enumerate(batcher.global_arrays(0)):
        if step >= n_steps:
            break
        trainer.state, m = trainer._train_step(trainer.state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    return losses


def test_ep_sharded_matches_single_device(devices8):
    """ep4 (experts sharded, tokens all-to-all'd) must train identically
    to the same model on one device — the MoE analogue of the dp==1-dev
    parity test."""
    single = _train_losses(MeshConfig(), devices8[:1])
    ep = _train_losses(MeshConfig(dp=-1, ep=4), devices8)
    np.testing.assert_allclose(ep, single, atol=3e-5)


def test_ep_with_tp_matches_single_device(devices8):
    """ep2×tp2×dp2: expert axis composes with tensor parallelism."""
    single = _train_losses(MeshConfig(), devices8[:1])
    mixed = _train_losses(MeshConfig(dp=2, ep=2, tp=2), devices8)
    np.testing.assert_allclose(mixed, single, atol=3e-5)


def test_moe_params_sharded_over_expert_axis(devices8):
    mesh = build_mesh(MeshConfig(dp=-1, ep=4), devices=devices8)
    model_cfg = _moe_cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    sh = param_shardings(params, mesh)
    moe = sh["backbone"]["encoder"]["layer_1"]["moe"]
    assert moe["wi"].spec == P("expert")
    assert moe["wo"].spec == P("expert")
    assert moe["router"].spec == P()
    # dense layer_0 untouched
    assert "ffn" in sh["backbone"]["encoder"]["layer_0"]


def test_aux_loss_reaches_training_loss(devices8):
    """The sowed load-balance loss must flow into the optimized loss:
    a model trained with a huge router_aux_coef reports a visibly larger
    loss than the same model with coef 0."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(16, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    losses = {}
    for coef in (0.0, 100.0):
        cfg = TrainConfig(dtype="float32", log_every_steps=0,
                          rng_impl="threefry")
        model_cfg = _moe_cfg(router_aux_coef=coef)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        batch = next(batcher.global_arrays(0))
        _, m = trainer._train_step(trainer.state, batch)
        losses[coef] = float(jax.device_get(m["loss"]))
    # aux >= coef * 1.0 (Switch bound), so the gap must exceed ~99
    assert losses[100.0] > losses[0.0] + 50.0


def test_moe_export_reload_roundtrip(tmp_path):
    """save_pretrained of an MoE model persists the expert/router weights
    (moe.safetensors sidecar + MoE fields in config.json) and
    from_pretrained rebuilds the identical model — no silent weight loss."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    model_cfg = _moe_cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, "bert", model_cfg)

    model2, params2, family, cfg2 = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2)
    assert cfg2.num_experts == 4 and cfg2.expert_top_k == 2
    moe1 = params["backbone"]["encoder"]["layer_1"]["moe"]
    moe2 = params2["backbone"]["encoder"]["layer_1"]["moe"]
    for key in ("router", "wi", "wo"):
        np.testing.assert_array_equal(np.asarray(moe1[key]), np.asarray(moe2[key]))


def test_moe_upcycling_dense_checkpoint(tmp_path):
    """Loading a DENSE checkpoint with num_experts>0 (upcycling) must not
    crash: MoE params stay fresh, dense weights load."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    dense_cfg = _moe_cfg(num_experts=0)
    model = BertForSequenceClassification(dense_cfg, num_labels=2)
    params = init_params(model, dense_cfg)
    out = str(tmp_path / "dense")
    auto_models.save_pretrained(out, params, "bert", dense_cfg)

    _, up_params, _, up_cfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2, num_experts=4)
    assert up_cfg.num_experts == 4
    assert "moe" in up_params["backbone"]["encoder"]["layer_1"]
    # dense weights actually loaded (not re-initialized)
    np.testing.assert_array_equal(
        np.asarray(params["backbone"]["embeddings"]["word_embeddings"]["embedding"]),
        np.asarray(up_params["backbone"]["embeddings"]["word_embeddings"]["embedding"]))


def test_moe_sidecar_layout_mismatch_raises(tmp_path):
    """Reloading an MoE export with a different moe_every must fail
    loudly — silently training random experts is the failure mode."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    model_cfg = _moe_cfg(num_layers=4)   # experts at layers 1, 3
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    out = str(tmp_path / "moe4")
    auto_models.save_pretrained(out, params, "bert", model_cfg)
    # either guard may fire first: the strict-backbone check (dense FFN
    # missing where a layer went MoE→dense) or the sidecar layout check
    with pytest.raises(ValueError, match="sidecar|missing"):
        auto_models.from_pretrained(out, task="seq-cls", moe_every=4)


def test_moe_rejected_for_unsupported_families(tmp_path):
    """T5 (own config class) and ALBERT (one shared layer) cannot host
    per-layer expert banks — from_pretrained must fail loudly, not
    silently train a dense model."""
    import json

    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

    d = tmp_path / "albert"
    d.mkdir()
    (d / "config.json").write_text(json.dumps({
        "model_type": "albert", "vocab_size": 128, "hidden_size": 32,
        "embedding_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 32}))
    with pytest.raises(ValueError, match="not supported"):
        auto_models.from_pretrained(str(d), task="seq-cls", num_experts=4)


def test_divergence_check_tolerates_expert_sharding(devices8):
    """Expert-sharded weights legitimately differ across the expert
    axis; the replica-divergence check must not flag them — but must
    still catch a perturbed replica of a replicated param."""
    mesh = build_mesh(MeshConfig(dp=2, ep=4), devices=devices8)
    model_cfg = _moe_cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg)
    cfg = TrainConfig(dtype="float32", log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    assert trainer.check_replica_divergence() < 1e-6


# --- GPT-2 decoder MoE (Mixtral-style; shared MoeFeedForward) -------------

def _gpt2_moe_cfg(**kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
    )
    base = dict(vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ,
                hidden_dropout=0.0, embd_dropout=0.0, attention_dropout=0.0,
                num_experts=4, expert_top_k=2)
    base.update(kw)
    return Gpt2Config(**base)


def test_gpt2_moe_training_learns(devices8):
    """GPT-2 with a token-routed expert MLP on every 2nd block trains
    causal-lm end to end on a dp×ep mesh (decoder MoE — the same
    MoeFeedForward the encoder families share, aux loss included)."""
    import jax

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )

    mesh = build_mesh(MeshConfig(dp=-1, ep=2), devices=devices8)
    model_cfg = _gpt2_moe_cfg()
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg, seed=0)
    assert "moe" in params["backbone"]["h_1"]      # GShard placement
    assert "mlp" in params["backbone"]["h_0"]
    cfg = TrainConfig(task="causal-lm", dtype="float32", learning_rate=3e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=2, num_experts=4, ep=2)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    assert hist["loss"][-1] < hist["loss"][0]
    assert np.isfinite(hist["loss"][-1])


def test_gpt2_moe_export_reload_roundtrip(tmp_path):
    """GPT-2 MoE export persists the expert bank (moe.safetensors
    sidecar + MoE fields in config.json) and reloads identically."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )

    model_cfg = _gpt2_moe_cfg()
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg)
    out = str(tmp_path / "gpt2-moe")
    auto_models.save_pretrained(out, params, "gpt2", model_cfg)

    _, params2, family, cfg2 = auto_models.from_pretrained(
        out, task="causal-lm")
    assert family == "gpt2"
    assert cfg2.num_experts == 4 and cfg2.expert_top_k == 2
    moe1 = params["backbone"]["h_1"]["moe"]
    moe2 = params2["backbone"]["h_1"]["moe"]
    for key in ("router", "wi", "wo"):
        np.testing.assert_array_equal(np.asarray(moe1[key]),
                                      np.asarray(moe2[key]))


def test_gpt2_moe_generation_works(tmp_path):
    """Decode path with MoE blocks: cached greedy generation runs (MoE
    has no cache state of its own — routing is per-step stateless)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
    )

    model_cfg = _gpt2_moe_cfg()
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg, seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(5, 250, (2, 6)), jnp.int32)
    mask = jnp.ones((2, 6), jnp.int32)
    out = generate_causal(model, params, ids, mask, max_new_tokens=4)
    assert np.asarray(out).shape == (2, 4)


def test_gpt2_moe_rejects_pipeline():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )

    cfg = _gpt2_moe_cfg(pipeline_stages=2)
    model = Gpt2LMHeadModel(cfg)
    with pytest.raises(ValueError, match="num_experts"):
        init_params(model, cfg)


def test_gpt2_moe_aux_loss_flows_through_fused_ce(devices8):
    """The fused losses must route through the Trainer's wrapped
    apply_fn so MoE router aux losses are collected (a direct
    model.apply drops flax sow silently): fused and unfused training
    losses must MATCH on an MoE model — both including aux."""
    import jax

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        make_fused_causal_lm_loss,
    )

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(16, seed=2)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)

    def first_loss(fused):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        model_cfg = _gpt2_moe_cfg(hidden_size=128, intermediate_size=256,
                                  router_aux_coef=1.0)  # aux is VISIBLE
        model = Gpt2LMHeadModel(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          num_experts=4, fused_vocab_ce=fused)
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            trainer.loss_fn = make_fused_causal_lm_loss(model,
                                                        interpret=True)
        batch = next(ShardedBatcher(ds, 16, mesh, shuffle=False,
                                    seed=0).global_arrays(0))
        _, m = trainer._train_step(trainer.state, batch)
        return float(jax.device_get(m["loss"]))

    np.testing.assert_allclose(first_loss(True), first_loss(False),
                               rtol=2e-5)


def test_causal_slot_priority_no_future_leak():
    """Position-major slot assignment (``causal=True``): under capacity
    congestion, changing the LAST token of a sequence must not change
    the MoE output at any earlier position. Round-major (encoder)
    priority violates this by design — a late token's top-1 can displace
    an early token's top-2 — which is exactly the future-token channel a
    causal LM must not have."""
    cfg = _moe_cfg(expert_capacity_factor=0.3)   # heavy congestion
    layer = MoeFeedForward(cfg, causal=True)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (2, SEQ, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]

    y, _ = layer.apply({"params": params}, x, mutable=["losses"])
    # perturb only the final position (both rows)
    x2 = x.at[:, -1, :].set(jax.random.normal(jax.random.PRNGKey(2),
                                              (2, 32), jnp.float32))
    y2, _ = layer.apply({"params": params}, x2, mutable=["losses"])
    np.testing.assert_array_equal(jax.device_get(y[:, :-1]),
                                  jax.device_get(y2[:, :-1]))


def test_round_major_priority_is_not_causal():
    """Sanity check that the default (round-major) priority DOES react
    to future tokens under the same congestion — i.e. the causal mode
    is a real behavioral switch, not a no-op."""
    cfg = _moe_cfg(expert_capacity_factor=0.3)
    layer = MoeFeedForward(cfg)                  # causal=False
    x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    y, _ = layer.apply({"params": params}, x, mutable=["losses"])
    x2 = x.at[:, -1, :].set(jax.random.normal(jax.random.PRNGKey(2),
                                              (2, 32), jnp.float32))
    y2, _ = layer.apply({"params": params}, x2, mutable=["losses"])
    assert not np.array_equal(jax.device_get(y[:, :-1]),
                              jax.device_get(y2[:, :-1]))


@pytest.mark.slow
def test_gpt2_moe_residual_flow_init():
    """The expert output projection follows GPT-2's 1/sqrt(2*n_layer)
    residual-flow init (like attn c_proj and dense mlp fc_out), and the
    other expert weights keep the plain initializer_range."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2LMHeadModel,
    )

    model_cfg = _gpt2_moe_cfg(num_layers=8, hidden_size=64,
                              intermediate_size=128, moe_every=2)
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg, seed=0)
    moe = params["backbone"]["h_1"]["moe"]
    expected = model_cfg.initializer_range / (2 * model_cfg.num_layers) ** 0.5
    assert np.std(np.asarray(moe["wo"])) == pytest.approx(expected, rel=0.15)
    assert np.std(np.asarray(moe["wi"])) == pytest.approx(
        model_cfg.initializer_range, rel=0.15)
