"""Telemetry subsystem tests (ISSUE 1): span nesting/ordering, JSONL
schema round-trip, crash-safe append, heartbeat stall dump under a
deliberately blocked thread, watchdog no-op on the CPU backend, the
zero-cost disabled path, StepMeter compile exclusion, and the
end-to-end trainer wiring (a real fit leaves schema-valid artifacts).
"""

import json
import os
import threading
import time

import numpy as np
import pytest
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.core import NULL_SPAN
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.watchdog import (
    CompileTracker,
    Heartbeat,
    sample_device_memory,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.utils.timing import StepMeter


@pytest.fixture()
def obs_dir(tmp_path):
    """File-backed telemetry into a fresh dir; restores the process
    default (enabled, no sink) afterwards so other tests never write."""
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    yield out
    obs.reset()


def _events(out):
    path = out / "events.jsonl"
    if not path.exists():
        return []  # lazy open: no file until the first event lands
    return [e for _, e, err in obs.iter_events(str(path)) if err is None]


# -- spans -------------------------------------------------------------------

def test_span_nesting_and_ordering(obs_dir):
    with obs.span("outer"):
        time.sleep(0.01)
        with obs.span("inner"):
            time.sleep(0.01)
    spans = {e["name"]: e for e in _events(obs_dir) if e["type"] == "span"}
    outer, inner = spans["outer"], spans["inner"]
    assert inner["depth"] == outer["depth"] + 1
    # containment: inner's [start, end] inside outer's
    assert inner["mono"] >= outer["mono"]
    assert inner["mono"] + inner["dur"] <= outer["mono"] + outer["dur"] + 1e-6
    # the inner span ENDS first, so it must have been emitted first
    names = [e["name"] for e in _events(obs_dir) if e["type"] == "span"]
    assert names == ["inner", "outer"]


def test_trace_json_projection(obs_dir):
    with obs.span("a"):
        pass
    obs.flush()
    n, errors = obs.validate_trace_file(str(obs_dir / "trace.json"))
    assert n == 1 and errors == []
    doc = json.loads((obs_dir / "trace.json").read_text())
    ev = doc["traceEvents"][0]
    assert ev["ph"] == "X" and ev["name"] == "a" and ev["dur"] >= 0


# -- schema round-trip -------------------------------------------------------

def test_jsonl_schema_round_trip(obs_dir):
    obs.scalar("train/loss", 0.5, 3)
    obs.scalar("train/null_ok", None)
    with obs.span("s", {"k": 1}):
        pass
    count, errors = obs.validate_events_file(str(obs_dir / "events.jsonl"))
    assert errors == []
    assert count >= 3  # run + metric + metric + span
    metric = [e for e in _events(obs_dir)
              if e["type"] == "metric" and e["name"] == "train/loss"][0]
    assert metric["value"] == 0.5 and metric["step"] == 3
    for e in _events(obs_dir):
        assert obs.validate_event(e) == []


def test_crash_safe_append_torn_tail(obs_dir):
    obs.scalar("a", 1.0)
    obs.scalar("b", 2.0)
    path = obs_dir / "events.jsonl"
    with open(path, "a") as f:
        f.write('{"v": 1, "t": 123.0, "host": 0, "pid": 1, "type": "met')
    # the torn FINAL line (kill mid-write) is skipped, prior events read
    count, errors = obs.validate_events_file(str(path))
    assert errors == [] and count >= 3
    # torn MIDDLE line = corruption, reported
    with open(path, "a") as f:
        f.write('\n{"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": '
                '"metric", "name": "c", "value": 3.0}\n')
    count2, errors2 = obs.validate_events_file(str(path))
    assert any("unparseable" in e for e in errors2)
    assert count2 == count + 1


def test_schema_rejects_bad_events():
    assert obs.validate_event([]) != []
    assert any("missing envelope" in e for e in obs.validate_event({}))
    good = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
            "name": "x", "value": 1.0}
    assert obs.validate_event(good) == []
    assert any("unknown event type" in e for e in obs.validate_event(
        {**good, "type": "nope"}))
    assert obs.validate_event({**good, "value": "high"}) != []
    missing = dict(good)
    del missing["name"]
    assert any("missing field 'name'" in e
               for e in obs.validate_event(missing))


# -- disabled path -----------------------------------------------------------

def test_disabled_is_allocation_free_and_writes_nothing(tmp_path):
    out = tmp_path / "t"
    obs.reset(out_dir=str(out), enabled=False)
    try:
        # the disabled span is ONE shared singleton: no per-call objects
        s1 = obs.span("train/step")
        s2 = obs.span("data/next_batch")
        assert s1 is s2 is NULL_SPAN
        with s1:
            pass
        obs.scalar("train/loss", 1.0, 0)
        obs.pulse()
        obs.flush()
        assert not (out / "events.jsonl").exists()
        assert obs.state().spans == []
    finally:
        obs.reset()


def test_env_disable(monkeypatch, tmp_path):
    monkeypatch.setenv(obs.ENV_ENABLE, "0")
    monkeypatch.setenv(obs.ENV_DIR, str(tmp_path / "x"))
    state = obs.reset()
    try:
        assert not state.enabled
        with obs.span("a"):
            pass
        assert not (tmp_path / "x" / "events.jsonl").exists()
    finally:
        monkeypatch.delenv(obs.ENV_ENABLE)
        monkeypatch.delenv(obs.ENV_DIR)
        obs.reset()


# -- heartbeat + stall dump --------------------------------------------------

def test_heartbeat_liveness_and_stall_dump(obs_dir):
    hb = Heartbeat(obs.state(), interval=0.05, stall_after=0.15,
                   sample_memory=False)
    release = threading.Event()

    def blocked_loop():
        hb.watch_current_thread()
        hb.pulse()
        release.wait(5.0)  # deliberately blocked: no pulses

    th = threading.Thread(target=blocked_loop, name="toy-train-loop")
    th.start()
    hb.start()
    try:
        deadline = time.time() + 3.0
        while hb.stall_count == 0 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        release.set()
        th.join()
        hb.stop()
    assert hb.stall_count >= 1
    events = _events(obs_dir)
    assert any(e["type"] == "heartbeat" for e in events)
    stalls = [e for e in events if e["type"] == "stall"]
    assert stalls, "stall dump never fired"
    dump = stalls[0]
    # names the blocked thread and carries its stack
    assert dump["stalled"] == "toy-train-loop"
    watched = [t for t in dump["threads"] if t.get("watched")]
    assert watched and watched[0]["name"] == "toy-train-loop"
    assert any("blocked_loop" in ln for ln in watched[0]["stack"])
    assert obs.validate_event(dump) == []


def test_heartbeat_rearms_after_pulse_resumes(obs_dir):
    hb = Heartbeat(obs.state(), interval=0.04, stall_after=0.1,
                   sample_memory=False)
    hb.watch_current_thread()
    hb.start()
    try:
        time.sleep(0.3)            # first stall
        assert hb.stall_count == 1  # fires once per episode, not per beat
        hb.pulse()
        time.sleep(0.3)            # second stall episode
        assert hb.stall_count == 2
    finally:
        hb.stop()


def test_unwatch_stops_stall_detection(obs_dir):
    hb = Heartbeat(obs.state(), interval=0.04, stall_after=0.1,
                   sample_memory=False)
    hb.watch_current_thread()
    hb.unwatch()
    hb.start()
    try:
        time.sleep(0.3)
        assert hb.stall_count == 0
    finally:
        hb.stop()


# -- watchdogs on CPU --------------------------------------------------------

def test_memory_sampler_noop_on_cpu(obs_dir):
    jax.devices()  # backend initialized (CPU under JAX_PLATFORMS=cpu)
    before = len(_events(obs_dir))
    assert sample_device_memory(obs.state()) == 0
    assert len(_events(obs_dir)) == before  # no memory events emitted


def test_compile_tracker_counts_compile_events(obs_dir):
    tracker = CompileTracker(obs.state())
    tracker.observe("/jax/core/compile/backend_compile_duration", 1.5)
    tracker.observe("/jax/core/something_else", 9.0)  # ignored
    tracker.observe("/jax/pjit/compile", 0.5)
    assert tracker.count == 2
    assert tracker.cum_secs == pytest.approx(2.0)
    compiles = [e for e in _events(obs_dir) if e["type"] == "compile"]
    assert [c["count"] for c in compiles] == [1, 2]
    assert compiles[-1]["cum"] == pytest.approx(2.0)
    for c in compiles:
        assert obs.validate_event(c) == []


# -- StepMeter compile exclusion --------------------------------------------

def test_stepmeter_excludes_recompile_steps():
    meter = StepMeter(n_chips=1, skip_first=1)
    for recompiled in (False, True, False, False, True, False):
        meter.start_step()
        time.sleep(0.03 if recompiled else 0.001)  # compiles are slow
        meter.end_step(8, recompiled=recompiled)
    # 6 steps: first skipped + 2 recompiles excluded → 3 measured
    assert meter._measured_steps == 3
    assert meter.excluded_steps == 3
    # throughput reflects steady-state: avg step ≈ 1ms, not ~12ms
    assert meter.avg_step_time < 0.02


def test_stepmeter_window_exclusion_and_sink(tmp_path):
    class Sink:
        def __init__(self):
            self.rows = []

        def scalar(self, name, value, step=None, args=None):
            self.rows.append((name, value, step))

    sink = Sink()
    meter = StepMeter(n_chips=2, sink=sink)
    meter.begin_window()
    meter.window_step(16)
    meter.window_step(16)
    time.sleep(0.01)
    meter.end_window()
    assert meter._measured_samples == 32 and meter._measured_steps == 2
    assert sink.rows and sink.rows[0][0] == "train/samples_per_sec"
    # the trainer's recompile pattern: a compiling step is dispatched,
    # then excluded + window restarted — measured counters untouched
    meter.begin_window()
    meter.window_step(16)
    meter.exclude_step(16)
    meter.begin_window()
    meter.window_step(16)
    meter.end_window()
    assert meter._measured_samples == 48
    assert meter.excluded_steps == 1
    assert meter._steps == 4


# -- prefetch wait attribution ----------------------------------------------

def test_prefetch_wait_attribution(obs_dir):
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
        PrefetchIterator,
    )

    def slow_producer():
        for i in range(4):
            time.sleep(0.02)
            yield i

    it = PrefetchIterator(slow_producer(), depth=1)
    got = list(it)
    assert got == [0, 1, 2, 3]
    # consumer drained instantly → it waited on the slow producer
    assert it.stats.consumer_wait > 0.01
    waits = [e for e in _events(obs_dir) if e["type"] == "metric"
             and e["name"] == "data/consumer_wait_s"]
    assert waits and waits[0]["args"]["verdict"] == "input_bound"
    assert waits[0]["args"]["batches"] == 4


# -- end-to-end trainer wiring ----------------------------------------------

def test_trainer_fit_emits_schema_valid_telemetry(obs_dir, tmp_path):
    from tests.test_trainer import _data, _tiny_model
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
        TrainConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ShardedBatcher,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    cfg = TrainConfig(epochs=1, train_batch_size=2, dtype="float32",
                      scale_lr_by_world_size=False,
                      output_data_dir=str(tmp_path), log_every_steps=2)
    mesh = build_mesh(MeshConfig())
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(_data(n=64), 16, mesh, shuffle=False, seed=0)
    hist = trainer.fit(batcher)
    assert hist["train_runtime"] > 0
    count, errors = obs.validate_events_file(str(obs_dir / "events.jsonl"))
    assert errors == [] and count > 0
    events = _events(obs_dir)
    names = {e.get("name") for e in events if e["type"] == "metric"}
    assert "train/loss" in names
    assert "train/samples_per_sec" in names            # meter → sink
    assert "train/step_time_hosts_mean" in names       # straggler stats
    span_names = {e["name"] for e in events if e["type"] == "span"}
    assert "train/step_dispatch" in span_names
    assert "train/sync" in span_names
    assert "xla/compile_wait" in span_names
    n_trace, trace_errors = obs.validate_trace_file(
        str(obs_dir / "trace.json"))
    assert trace_errors == [] and n_trace > 0
    stats = [e for e in events if e["type"] == "metric"
             and e["name"] == "train/step_time_hosts_mean"][0]
    assert stats["args"]["n_hosts"] == 1
    assert stats["args"]["straggler_ratio"] == 1.0


def test_trainer_disabled_telemetry_unchanged(tmp_path):
    obs.reset(enabled=False)
    try:
        from tests.test_trainer import _data, _tiny_model
        from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
            TrainConfig,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
            ShardedBatcher,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
            MeshConfig,
            build_mesh,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.train import (
            Trainer,
        )

        cfg = TrainConfig(epochs=1, train_batch_size=2, dtype="float32",
                          scale_lr_by_world_size=False,
                          output_data_dir=str(tmp_path), log_every_steps=0)
        mesh = build_mesh(MeshConfig())
        model, params = _tiny_model()
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(_data(n=32), 16, mesh, shuffle=False,
                                 seed=0)
        hist = trainer.fit(batcher)
        assert hist["train_samples_per_second"] > 0
        assert obs.state().spans == []  # nothing recorded anywhere
    finally:
        obs.reset()


def test_generate_emits_tokens_per_sec(obs_dir):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=16, num_layers=1,
                     num_heads=2, intermediate_size=32,
                     max_position_embeddings=64, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg, seed=0)
    prompts = np.ones((2, 4), np.int32)
    out = generate_causal(model, params, prompts, max_new_tokens=4)
    assert out.shape == (2, 4)
    events = _events(obs_dir)
    toks = [e for e in events if e["type"] == "metric"
            and e["name"] == "generate/causal/tokens_per_sec"]
    assert toks and toks[0]["value"] > 0
    assert toks[0]["args"] == {"batch": 2, "new_tokens": 4}
