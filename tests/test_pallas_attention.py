"""Pallas fused-attention numerics vs the XLA reference implementation
(interpret mode on CPU; the same kernel runs compiled on TPU)."""

import numpy as np
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    make_attention_mask,
    xla_attention,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.pallas_attention import (
    flash_attention,
)


def _qkv(b=2, h=2, s=64, d=32, seed=0, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.normal(size=(b, h, s, d)), dtype)
    return mk(), mk(), mk()


def test_matches_xla_no_mask():
    q, k, v = _qkv()
    out = flash_attention(q, k, v, block_q=32, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_matches_xla_with_padding_mask():
    q, k, v = _qkv(seed=1)
    pad = np.ones((2, 64), np.int32)
    pad[0, 40:] = 0
    pad[1, 10:] = 0
    mask = make_attention_mask(jnp.asarray(pad))
    out = flash_attention(q, k, v, mask=mask, block_q=32, interpret=True)
    ref = xla_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_bf16_inputs():
    q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=32, interpret=True)
    ref = xla_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref, np.float32),
                               atol=2e-2)


def test_fallback_on_odd_lengths():
    q, k, v = _qkv(s=60)  # 60 % 32 != 0 with block 32... use block_q default
    out = flash_attention(q, k, v, block_q=64, interpret=True)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_fallback_on_general_mask():
    q, k, v = _qkv(seed=3)
    full = jnp.zeros((2, 2, 64, 64))
    out = flash_attention(q, k, v, mask=full, interpret=True)
    ref = xla_attention(q, k, v, mask=full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_qkv_grads_match_xla():
    """The fused Pallas backward (dQ / dK-dV kernels) against XLA autodiff."""
    import jax

    q, k, v = _qkv(s=256, d=32, seed=4)
    pad = np.ones((2, 256), np.int32)
    pad[0, 200:] = 0
    mask = make_attention_mask(jnp.asarray(pad))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, mask, block_q=64, block_k=64, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(lambda q, k, v: xla_attention(q, k, v, mask=mask)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_flash_causal_matches_xla_fwd_and_bwd():
    import jax

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        make_causal_mask,
    )

    q, k, v = _qkv(s=128, d=32, seed=5)
    out = flash_attention(q, k, v, block_q=32, block_k=32, causal=True,
                          interpret=True)
    ref = xla_attention(q, k, v, mask=make_causal_mask(128))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, block_q=32, block_k=32, causal=True, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(lambda q, k, v: jnp.sum(xla_attention(
        q, k, v, mask=make_causal_mask(128)) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_flash_blocked_kv_matches_whole_kv():
    """Online-softmax across kv blocks == single-block softmax."""
    q, k, v = _qkv(s=256, d=32, seed=6)
    out_blocked = flash_attention(q, k, v, block_q=64, block_k=64,
                                  interpret=True)
    out_whole = flash_attention(q, k, v, block_q=256, block_k=256,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(out_blocked), np.asarray(out_whole),
                               atol=1e-5)


def test_flash_mask_gradient_nonzero():
    """The additive mask is a differentiable input (learned biases)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        make_attention_mask,
        xla_attention,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.pallas_attention import (
        flash_attention,
    )

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 2, 128, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 2, 128, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 2, 128, 16), jnp.float32)
    mask = make_attention_mask(jnp.ones((2, 128), jnp.int32)) * 0.0
    gf = jax.grad(lambda m: jnp.sum(flash_attention(q, k, v, m) ** 2))(mask)
    gx = jax.grad(lambda m: jnp.sum(xla_attention(q, k, v, m) ** 2))(mask)
    assert float(jnp.max(jnp.abs(gf))) > 0
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gx), atol=1e-4)


def test_flash_sliding_window_matches_banded_xla():
    """Banded flash (causal + window): fwd and all grads must match XLA
    with an explicit band mask — at a multi-tile shape where whole tiles
    fall BELOW the band and are skipped."""
    import jax

    B, H, S, D = 2, 2, 256, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32) * 0.1
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32) * 0.1
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32) * 0.1
    pad = np.zeros((B, 1, 1, S), np.float32)
    pad[0, ..., -32:] = -1e9
    pad = jnp.asarray(pad)

    for window in (48, 128):
        i = jnp.arange(S)[:, None]
        j = jnp.arange(S)[None, :]
        band = jnp.where((j <= i) & (j > i - window), 0.0,
                         -1e9)[None, None].astype(jnp.float32)

        # block 64: with window 48 every tile 2+ below the diagonal is
        # fully outside the band → exercises the tile-skip predicate
        out_f = flash_attention(q, k, v, mask=pad, causal=True,
                                window=window, block_q=64, block_k=64,
                                interpret=True)
        out_x = xla_attention(q, k, v, mask=pad + band)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                                   atol=2e-5, rtol=1e-4)

        def lf(q, k, v):
            return jnp.sum(flash_attention(q, k, v, mask=pad, causal=True,
                                           window=window, block_q=64,
                                           block_k=64,
                                           interpret=True) ** 2)

        def lx(q, k, v):
            return jnp.sum(xla_attention(q, k, v, mask=pad + band) ** 2)

        gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(lx, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=1e-3)
