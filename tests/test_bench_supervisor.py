"""Supervisor-layer tests for ``bench.py`` (no JAX backend, no child
process): the outage contract (structured error lines, rc 0) and the
kernel-parity fold-in on the headline line (VERDICT r4 #1/#2). The
measured bodies run on the real chip; what these tests pin is the
plumbing that must not lose evidence when the tunnel flaps.
"""

import argparse
import json
import subprocess
import types

import bench

_DEFAULT_PARITY = {"pass": 8, "fail": 0, "subset": True, "rc": 0}


def _args(**kw):
    base = dict(model=None, buckets=False, mesh=False, generate=False,
                causal_lm=False, mlm=False, lora=False, banded=False,
                llama_train=False, mixtral_train=False, batch=None,
                opt_state_bf16=False, remat_policy=None,
                budget_seconds=None)
    base.update(kw)
    ns = argparse.Namespace(**base)
    setattr(ns, "_child", False)
    return ns


def _data_lines(lines):
    """Drop the provisional progress lines (they are parseable JSON with
    ``provisional: true``) — what remains is the measurement contract."""
    out = []
    for ln in lines:
        try:
            rec = json.loads(ln)
        except ValueError:
            out.append(ln)
            continue
        if not rec.get("provisional"):
            out.append(ln)
    return out


def _run(monkeypatch, capsys, args, child_stdout, parity=_DEFAULT_PARITY,
         probe_ok=True):
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda deadline=None: (
            {"ok": True, "platform": "tpu", "n": 1,
             "device_kind": "TPU v5 lite"} if probe_ok
            else {"ok": False, "attempts": [{"attempt": 1,
                                             "outcome": "timeout>5s"}]}))
    if parity is not None:       # None → leave run_kernel_parity as-is
        monkeypatch.setattr(bench, "run_kernel_parity", lambda: parity)
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: types.SimpleNamespace(returncode=0,
                                              stdout=child_stdout))
    bench.supervise(args)
    return capsys.readouterr().out.strip().splitlines()


def test_unreachable_backend_emits_structured_error(monkeypatch, capsys):
    lines = _run(monkeypatch, capsys, _args(), "", probe_ok=False)
    data = _data_lines(lines)
    assert len(data) == 1
    rec = json.loads(data[0])
    assert rec["metric"] == "bert_base_finetune_samples_per_sec_per_chip"
    assert rec["value"] is None
    assert rec["error"] == "backend_unreachable"
    assert rec["detail"]["attempts"]


def test_every_line_is_parseable_and_never_empty(monkeypatch, capsys):
    """The r05 empty-tail fix: from the FIRST line of stdout, a driver
    that kills this process at any point finds a parseable JSON tail
    naming the stage that was running."""
    lines = _run(monkeypatch, capsys, _args(), "", probe_ok=False)
    assert lines, "no output at all"
    for ln in lines:
        json.loads(ln)
    first = json.loads(lines[0])
    assert first["provisional"] is True
    assert first["stage"] == "probing"
    assert first["metric"] == "bert_base_finetune_samples_per_sec_per_chip"


def test_headline_carries_kernel_parity_field(monkeypatch, capsys):
    child = json.dumps({"metric": "bert_base_finetune_samples_per_sec_per_chip",
                        "value": 277.4, "unit": "samples/sec/chip",
                        "vs_baseline": 8.669})
    lines = _run(monkeypatch, capsys, _args(), child + "\n",
                 parity={"pass": 8, "fail": 0, "subset": True, "rc": 0})
    rec = json.loads(lines[-1])
    assert not rec.get("provisional")
    assert rec["value"] == 277.4
    assert rec["kernel_parity"] == {"pass": 8, "fail": 0, "subset": True,
                                    "rc": 0}


def test_headline_preserves_extra_lines(monkeypatch, capsys):
    """Non-JSON prefix lines in the child's stdout survive the fold-in."""
    child = ("note line\n"
             + json.dumps({"metric":
                           "bert_base_finetune_samples_per_sec_per_chip",
                           "value": 1.0, "unit": "samples/sec/chip",
                           "vs_baseline": 0.03}))
    lines = _run(monkeypatch, capsys, _args(), child)
    data = _data_lines(lines)
    assert data[0] == "note line"
    assert "kernel_parity" in json.loads(lines[-1])


def test_sweep_variants_skip_parity(monkeypatch, capsys):
    """--batch/--opt-state-bf16 runs must NOT pay the parity subset."""
    child = json.dumps({"metric": "bert_base_finetune_samples_per_sec_per_chip",
                        "value": 250.0, "unit": "samples/sec/chip",
                        "vs_baseline": 7.8})

    def boom():
        raise AssertionError("parity must not run for sweep variants")

    monkeypatch.setattr(bench, "run_kernel_parity", boom)
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda deadline=None: {"ok": True, "platform": "tpu", "n": 1,
                               "device_kind": "TPU v5 lite"})
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: types.SimpleNamespace(returncode=0, stdout=child))
    bench.supervise(_args(batch=64))
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 250.0
    assert "kernel_parity" not in rec


def test_unparseable_headline_skips_parity_and_forwards(monkeypatch, capsys):
    """If the child's last line isn't JSON, don't burn parity minutes —
    forward the raw stdout unchanged."""

    def boom():
        raise AssertionError("parity must not run when the line is broken")

    monkeypatch.setattr(bench, "run_kernel_parity", boom)
    lines = _run(monkeypatch, capsys, _args(), "garbage not json\n",
                 parity=None)
    assert _data_lines(lines) == ["garbage not json"]


def test_child_timeout_emits_partial_stdout(monkeypatch, capsys):
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda deadline=None: {"ok": True, "platform": "tpu", "n": 1,
                               "device_kind": "TPU v5 lite"})

    def raise_timeout(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1800,
                                        output=b"partial training log")

    monkeypatch.setattr(bench.subprocess, "run", raise_timeout)
    bench.supervise(_args())
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "bench_timeout"
    assert "partial training log" in rec["detail"]["partial_stdout"]


def test_probe_backoff_is_capped(monkeypatch):
    """Retry waits follow 5*2^i capped at 60s (≈41 min total patience
    with 15 × 120s probe timeouts — the tunnel-flap timescale)."""
    waits = []
    monkeypatch.setattr(bench.time, "sleep", waits.append)

    def timeout_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", timeout_run)
    monkeypatch.setattr(bench, "PROBE_ATTEMPTS", 6)
    info = bench.probe_backend()
    assert info["ok"] is False and len(info["attempts"]) == 6
    assert waits == [5, 10, 20, 40, 60]


def test_child_timeout_forwards_partial_json_lines(monkeypatch, capsys):
    """A child killed by the deadline may have printed complete metric
    lines already — they must survive into the artifact ahead of the
    error line (partial results beat no results)."""
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda deadline=None: {"ok": True, "platform": "tpu", "n": 1,
                               "device_kind": "TPU v5 lite"})
    done = json.dumps({"metric": "generate_gpt2_greedy_tokens_per_sec_per_chip",
                       "value": 900.0, "unit": "tokens/sec/chip",
                       "vs_baseline": 0.0})
    partial = done + "\nhalf a li"

    def raise_timeout(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=60,
                                        output=partial.encode())

    monkeypatch.setattr(bench.subprocess, "run", raise_timeout)
    bench.supervise(_args(generate=True, budget_seconds=60))
    lines = capsys.readouterr().out.strip().splitlines()
    data = _data_lines(lines)
    assert json.loads(data[0])["value"] == 900.0
    tail = json.loads(lines[-1])
    assert tail["error"] == "bench_timeout"


def test_budget_caps_child_timeout_and_skips_parity(monkeypatch, capsys):
    """With --budget-seconds the child deadline derives from the budget
    (not the 30-min default) and the ~2-min parity subset is skipped
    when it can't fit in what remains."""
    seen = {}
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda deadline=None: {"ok": True, "platform": "tpu", "n": 1,
                               "device_kind": "TPU v5 lite"})

    def boom():
        raise AssertionError("parity must not run on a tight budget")

    monkeypatch.setattr(bench, "run_kernel_parity", boom)
    child = json.dumps({"metric":
                        "bert_base_finetune_samples_per_sec_per_chip",
                        "value": 260.0, "unit": "samples/sec/chip",
                        "vs_baseline": 8.1})

    def fake_run(*a, **k):
        seen["timeout"] = k.get("timeout")
        seen["env"] = k.get("env", {})
        return types.SimpleNamespace(returncode=0, stdout=child)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench.supervise(_args(budget_seconds=90))
    assert seen["timeout"] <= 90 + 11
    assert float(seen["env"]["_BENCH_CHILD_BUDGET"]) <= 90
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 260.0
    assert "kernel_parity" not in rec


def test_probe_respects_budget_deadline(monkeypatch):
    """Under a deadline the probe gives up when the budget is spent
    instead of burning its ~41-min retry patience."""
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    def timeout_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", timeout_run)
    info = bench.probe_backend(deadline=bench.time.monotonic() - 1)
    assert info["ok"] is False
    assert info["attempts"][-1]["outcome"] == "budget_exhausted"
    assert len(info["attempts"]) == 1


def test_install_child_budget_arms_alarm(monkeypatch):
    """The child-side deadline: SIGALRM/SIGTERM handlers installed and
    the alarm leads the budget by the 5s grace."""
    import signal as _signal

    armed = {}
    monkeypatch.setattr(_signal, "signal",
                        lambda sig, fn: armed.setdefault(sig, fn))
    monkeypatch.setattr(_signal, "alarm",
                        lambda s: armed.setdefault("alarm", s))
    monkeypatch.setenv("_BENCH_CHILD_BUDGET", "60")
    bench._install_child_budget(_args(budget_seconds=90))
    assert armed["alarm"] == 55
    assert _signal.SIGTERM in armed
    assert callable(armed[_signal.SIGTERM])


def test_parity_line_parser():
    """run_kernel_parity's PASS/FAIL accounting against canned output."""
    fake = types.SimpleNamespace(
        returncode=1,
        stdout=("backend: tpu (TPU v5 lite)\n"
                "PASS flash fwd (causal): ...\n"
                "FAIL flash bwd dq (causal): ...\n"
                "PASS vocab-ce loss (gpt2-vocab): ...\n"))
    orig = bench.subprocess.run
    bench.subprocess.run = lambda *a, **k: fake
    try:
        summary = bench.run_kernel_parity()
    finally:
        bench.subprocess.run = orig
    assert summary["pass"] == 2 and summary["fail"] == 1
    assert summary["failed"] == ["flash bwd dq (causal)"]
