"""Supervisor-layer tests for ``bench.py`` (no JAX backend, no child
process): the outage contract (structured error lines, rc 0) and the
kernel-parity fold-in on the headline line (VERDICT r4 #1/#2). The
measured bodies run on the real chip; what these tests pin is the
plumbing that must not lose evidence when the tunnel flaps.
"""

import argparse
import json
import subprocess
import types

import bench

_DEFAULT_PARITY = {"pass": 8, "fail": 0, "subset": True, "rc": 0}


def _args(**kw):
    base = dict(model=None, buckets=False, mesh=False, generate=False,
                causal_lm=False, mlm=False, lora=False, banded=False,
                llama_train=False, mixtral_train=False, batch=None,
                opt_state_bf16=False, remat_policy=None)
    base.update(kw)
    ns = argparse.Namespace(**base)
    setattr(ns, "_child", False)
    return ns


def _run(monkeypatch, capsys, args, child_stdout, parity=_DEFAULT_PARITY,
         probe_ok=True):
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda: ({"ok": True, "platform": "tpu", "n": 1,
                  "device_kind": "TPU v5 lite"} if probe_ok
                 else {"ok": False, "attempts": [{"attempt": 1,
                                                  "outcome": "timeout>5s"}]}))
    if parity is not None:       # None → leave run_kernel_parity as-is
        monkeypatch.setattr(bench, "run_kernel_parity", lambda: parity)
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: types.SimpleNamespace(returncode=0,
                                              stdout=child_stdout))
    bench.supervise(args)
    return capsys.readouterr().out.strip().splitlines()


def test_unreachable_backend_emits_structured_error(monkeypatch, capsys):
    lines = _run(monkeypatch, capsys, _args(), "", probe_ok=False)
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "bert_base_finetune_samples_per_sec_per_chip"
    assert rec["value"] is None
    assert rec["error"] == "backend_unreachable"
    assert rec["detail"]["attempts"]


def test_headline_carries_kernel_parity_field(monkeypatch, capsys):
    child = json.dumps({"metric": "bert_base_finetune_samples_per_sec_per_chip",
                        "value": 277.4, "unit": "samples/sec/chip",
                        "vs_baseline": 8.669})
    lines = _run(monkeypatch, capsys, _args(), child + "\n",
                 parity={"pass": 8, "fail": 0, "subset": True, "rc": 0})
    rec = json.loads(lines[-1])
    assert rec["value"] == 277.4
    assert rec["kernel_parity"] == {"pass": 8, "fail": 0, "subset": True,
                                    "rc": 0}


def test_headline_preserves_extra_lines(monkeypatch, capsys):
    """Non-JSON prefix lines in the child's stdout survive the fold-in."""
    child = ("note line\n"
             + json.dumps({"metric":
                           "bert_base_finetune_samples_per_sec_per_chip",
                           "value": 1.0, "unit": "samples/sec/chip",
                           "vs_baseline": 0.03}))
    lines = _run(monkeypatch, capsys, _args(), child)
    assert lines[0] == "note line"
    assert "kernel_parity" in json.loads(lines[-1])


def test_sweep_variants_skip_parity(monkeypatch, capsys):
    """--batch/--opt-state-bf16 runs must NOT pay the parity subset."""
    child = json.dumps({"metric": "bert_base_finetune_samples_per_sec_per_chip",
                        "value": 250.0, "unit": "samples/sec/chip",
                        "vs_baseline": 7.8})

    def boom():
        raise AssertionError("parity must not run for sweep variants")

    monkeypatch.setattr(bench, "run_kernel_parity", boom)
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda: {"ok": True, "platform": "tpu", "n": 1,
                 "device_kind": "TPU v5 lite"})
    monkeypatch.setattr(
        bench.subprocess, "run",
        lambda *a, **k: types.SimpleNamespace(returncode=0, stdout=child))
    bench.supervise(_args(batch=64))
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 250.0
    assert "kernel_parity" not in rec


def test_unparseable_headline_skips_parity_and_forwards(monkeypatch, capsys):
    """If the child's last line isn't JSON, don't burn parity minutes —
    forward the raw stdout unchanged."""

    def boom():
        raise AssertionError("parity must not run when the line is broken")

    monkeypatch.setattr(bench, "run_kernel_parity", boom)
    lines = _run(monkeypatch, capsys, _args(), "garbage not json\n",
                 parity=None)
    assert lines == ["garbage not json"]


def test_child_timeout_emits_partial_stdout(monkeypatch, capsys):
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda: {"ok": True, "platform": "tpu", "n": 1,
                 "device_kind": "TPU v5 lite"})

    def raise_timeout(*a, **k):
        raise subprocess.TimeoutExpired(cmd="x", timeout=1800,
                                        output=b"partial training log")

    monkeypatch.setattr(bench.subprocess, "run", raise_timeout)
    bench.supervise(_args())
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error"] == "bench_timeout"
    assert "partial training log" in rec["detail"]["partial_stdout"]


def test_probe_backoff_is_capped(monkeypatch):
    """Retry waits follow 5*2^i capped at 60s (≈41 min total patience
    with 15 × 120s probe timeouts — the tunnel-flap timescale)."""
    waits = []
    monkeypatch.setattr(bench.time, "sleep", waits.append)

    def timeout_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", timeout_run)
    monkeypatch.setattr(bench, "PROBE_ATTEMPTS", 6)
    info = bench.probe_backend()
    assert info["ok"] is False and len(info["attempts"]) == 6
    assert waits == [5, 10, 20, 40, 60]


def test_parity_line_parser():
    """run_kernel_parity's PASS/FAIL accounting against canned output."""
    fake = types.SimpleNamespace(
        returncode=1,
        stdout=("backend: tpu (TPU v5 lite)\n"
                "PASS flash fwd (causal): ...\n"
                "FAIL flash bwd dq (causal): ...\n"
                "PASS vocab-ce loss (gpt2-vocab): ...\n"))
    orig = bench.subprocess.run
    bench.subprocess.run = lambda *a, **k: fake
    try:
        summary = bench.run_kernel_parity()
    finally:
        bench.subprocess.run = orig
    assert summary["pass"] == 2 and summary["fail"] == 1
    assert summary["failed"] == ["flash bwd dq (causal)"]
