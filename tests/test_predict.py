"""scripts/predict.py surface tests: every task path produces the
documented JSON contract from an exported checkpoint."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
    Gpt2Config,
    Gpt2LMHeadModel,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig

import predict as predict_mod


def _bert_export(tmp_path):
    cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=32)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    out = str(tmp_path / "bert")
    auto_models.save_pretrained(out, params, "bert", cfg)
    return out


def _run(argv):
    # the REAL CLI parser — tests cannot drift from the tool
    args = predict_mod.build_parser().parse_args(
        argv + ["--max_seq_length", "32", "--max_new_tokens", "4"])
    return predict_mod.predict(args)


def test_predict_seq_cls(tmp_path):
    d = _bert_export(tmp_path)
    rows = _run(["--model_dir", d, "--task", "seq-cls",
                 "--text", "a fine film"])
    assert len(rows) == 1
    assert rows[0]["label"] in (0, 1)
    assert abs(sum(rows[0]["probs"]) - 1.0) < 1e-3


def test_predict_qa_and_batch_file(tmp_path):
    d = _bert_export(tmp_path)
    f = tmp_path / "in.jsonl"
    # second row has NO context — per-row optional
    f.write_text(json.dumps({"text": "who is it?", "context": "it is ada."}) + "\n"
                 + json.dumps({"text": "what now?"}) + "\n")
    rows = _run(["--model_dir", d, "--task", "qa",
                 "--input_file", str(f)])
    assert len(rows) == 2
    for r in rows:
        assert "answer" in r and r["end"] >= r["start"]
    # offset-decoded answers are exact NON-EMPTY substrings of the
    # original context (the joint search over a non-empty context always
    # yields a span) — the surface-text contract the EM/F1 metric scores;
    # a context-less row decodes to "" with -1/-1 span tokens
    assert rows[0]["answer"] and rows[0]["answer"] in "it is ada."
    assert rows[1]["answer"] == "" and rows[1]["start"] == -1


def test_predict_causal_lm(tmp_path):
    cfg = Gpt2Config(vocab_size=256, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64,
                     max_position_embeddings=64)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg)
    out = str(tmp_path / "gpt2")
    auto_models.save_pretrained(out, params, "gpt2", cfg)
    rows = _run(["--model_dir", out, "--task", "causal-lm",
                 "--text", "hello world"])
    assert len(rows[0]["generated_ids"]) == 4
    assert isinstance(rows[0]["generated"], str)


def test_predict_mlm_fills(tmp_path):
    """With a real WordPiece vocab the [MASK] token round-trips and the
    fill positions are reported with top tokens."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForMaskedLM,
    )

    vocab_words = ["[PAD]", "[CLS]", "[SEP]", "[UNK]", "[MASK]",
                   "the", "movie", "was", "good", "bad"]
    cfg = EncoderConfig(vocab_size=len(vocab_words), hidden_size=32,
                        num_layers=2, num_heads=4, intermediate_size=64,
                        max_position_embeddings=32, use_pooler=False)
    model = BertForMaskedLM(cfg)
    params = init_params(model, cfg)
    out = str(tmp_path / "mlm")
    auto_models.save_pretrained(out, params, "bert", cfg)
    (tmp_path / "mlm" / "vocab.txt").write_text("\n".join(vocab_words))
    rows = _run(["--model_dir", out, "--task", "mlm",
                 "--text", "the movie was [MASK]", "--top_k", "3"])
    assert rows[0]["fills"], "the [MASK] position must be found"
    assert len(rows[0]["fills"][0]["top_tokens"]) == 3


def test_predict_rtd(tmp_path):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.electra import (
        ElectraForPreTraining,
    )

    cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=32, use_pooler=False)
    model = ElectraForPreTraining(cfg)
    params = init_params(model, cfg)
    out = str(tmp_path / "rtd")
    auto_models.save_pretrained(out, params, "electra", cfg)
    rows = _run(["--model_dir", out, "--task", "rtd",
                 "--text", "a plain sentence"])
    assert len(rows[0]["tokens"]) == len(rows[0]["replaced_prob"])
    assert all(0.0 <= p <= 1.0 for p in rows[0]["replaced_prob"])


def test_predict_with_lora_adapter(tmp_path):
    """--adapter merges a LoRA sidecar onto the base checkpoint at load:
    predictions equal the merged-export model's exactly."""
    import numpy as np

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
        init_lora_params,
        lora_scaling,
        merge_lora,
        save_adapters,
    )

    cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=32)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg)
    base_dir = str(tmp_path / "base")
    auto_models.save_pretrained(base_dir, params, "bert", cfg)

    # nonzero adapters so the merge visibly changes the logits
    import jax
    import jax.numpy as jnp

    lora = init_lora_params(params, rank=4, targets="attention", seed=3)
    lora = jax.tree.map(
        lambda x: jnp.asarray(
            np.random.RandomState(0).normal(0, 0.1, x.shape), x.dtype),
        lora)
    adapter_dir = str(tmp_path / "adapter")
    save_adapters(adapter_dir, lora, rank=4, alpha=16.0,
                  targets="attention")
    merged_dir = str(tmp_path / "merged")
    auto_models.save_pretrained(
        merged_dir, merge_lora(params, lora, lora_scaling(4, 16.0)),
        "bert", cfg)

    out_adapter = _run(["--model_dir", base_dir, "--adapter", adapter_dir,
                        "--task", "seq-cls", "--text", "a fine day"])
    out_merged = _run(["--model_dir", merged_dir, "--task", "seq-cls",
                       "--text", "a fine day"])
    out_base = _run(["--model_dir", base_dir, "--task", "seq-cls",
                     "--text", "a fine day"])
    np.testing.assert_allclose(out_adapter[0]["probs"],
                               out_merged[0]["probs"], atol=1e-6)
    assert not np.allclose(out_adapter[0]["probs"], out_base[0]["probs"])
