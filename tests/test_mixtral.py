"""Mixtral — MoE in the Llama family (models/moe.py::MixtralMoeBlock).

HF torch parity (router softmax + top-2 renormalized gates + SwiGLU
experts), checkpoint round-trip through the HF expert layout (no
sidecar: Mixtral is the one MoE family HF defines a layout for),
dp×ep mesh training equivalence, and the capacity/composition rules.

Parity caveat: HF routes every token; our dispatch uses static GShard
capacity. At ``expert_capacity_factor >= num_experts / top_k`` the
capacity can never bind (a token contributes at most one assignment
per expert), so the two are numerically identical — parity tests load
with that override; training defaults keep the bounded capacity.
"""

import numpy as np
import pytest
import torch
import transformers
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)

TOL = 3e-4
NO_DROP = 4.0          # capacity factor at which dispatch never drops


@pytest.fixture(scope="module")
def mixtral_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        num_local_experts=4, num_experts_per_tok=2,
        sliding_window=None, rms_norm_eps=1e-5,
        bos_token_id=1, eos_token_id=2, pad_token_id=0,
        tie_word_embeddings=False, attention_dropout=0.0)
    d = str(tmp_path_factory.mktemp("mixtral"))
    transformers.MixtralForCausalLM(cfg).eval().save_pretrained(d)
    return d


def _inputs(batch=3, seq=10, vocab=128, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(3, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    return ids, mask


def test_mixtral_lm_parity(mixtral_dir):
    model, params, family, cfg = auto_models.from_pretrained(
        mixtral_dir, task="causal-lm", expert_capacity_factor=NO_DROP)
    assert family == "llama" and cfg.model_type == "mixtral"
    assert cfg.num_experts == 4 and cfg.expert_top_k == 2
    assert cfg.moe_every == 1
    m = transformers.MixtralForCausalLM.from_pretrained(mixtral_dir).eval()
    ids, mask = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids),
                  attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids),
                        jnp.asarray(mask), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


@pytest.mark.slow
def test_mixtral_export_roundtrip(mixtral_dir, tmp_path):
    """HF → ours → HF: the expert bank survives both conversion
    directions and transformers reloads our export bit-compatibly."""
    model, params, family, cfg = auto_models.from_pretrained(
        mixtral_dir, task="causal-lm", expert_capacity_factor=NO_DROP)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, cfg)
    import os
    assert not os.path.exists(os.path.join(out, "moe.safetensors"))

    m1 = transformers.MixtralForCausalLM.from_pretrained(mixtral_dir).eval()
    m2 = transformers.MixtralForCausalLM.from_pretrained(out).eval()
    ids, _ = _inputs()
    with torch.no_grad():
        a = m1(input_ids=torch.tensor(ids)).logits.numpy()
        b = m2(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)

    # and back through OUR loader: the folded tree matches the original
    _, params2, _, cfg2 = auto_models.from_pretrained(
        out, task="causal-lm")
    assert cfg2.num_experts == 4 and cfg2.model_type == "mixtral"
    moe1 = params["backbone"]["layers_0"]["moe"]
    moe2 = params2["backbone"]["layers_0"]["moe"]
    for key in ("router", "w1", "w2", "w3"):
        np.testing.assert_allclose(np.asarray(moe2[key]),
                                   np.asarray(moe1[key]), atol=1e-6)


def test_upcycle_dense_llama_roundtrips_as_mixtral(tmp_path):
    """MoE-upcycling a dense Llama checkpoint (num_experts override)
    coerces model_type to 'mixtral' so the expert bank survives export →
    reload (HF Mixtral is the only layout that can carry it); Qwen2 and
    Gemma variants are rejected (their knobs don't fit the layout)."""
    dense_cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=2,
                            num_heads=2, num_kv_heads=2,
                            intermediate_size=32,
                            max_position_embeddings=32)
    dense_params = init_params(LlamaForCausalLM(dense_cfg), dense_cfg)
    src = str(tmp_path / "dense")
    auto_models.save_pretrained(src, dense_params, "llama", dense_cfg)

    model, params, _, cfg = auto_models.from_pretrained(
        src, task="causal-lm", num_experts=2)
    assert cfg.model_type == "mixtral" and cfg.num_experts == 2
    out = str(tmp_path / "upcycled")
    auto_models.save_pretrained(out, params, "llama", cfg)
    _, params2, _, cfg2 = auto_models.from_pretrained(out, task="causal-lm")
    assert cfg2.num_experts == 2
    moe1 = params["backbone"]["layers_0"]["moe"]
    moe2 = params2["backbone"]["layers_0"]["moe"]
    for key in ("router", "w1", "w2", "w3"):
        np.testing.assert_allclose(np.asarray(moe2[key]),
                                   np.asarray(moe1[key]), atol=1e-6)

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        llama_config_from_hf,
    )

    with pytest.raises(ValueError, match="Mixtral"):
        llama_config_from_hf({"model_type": "qwen2", "vocab_size": 64,
                              "hidden_size": 16, "num_hidden_layers": 2,
                              "num_attention_heads": 2,
                              "intermediate_size": 32}, num_experts=2)
    # DIRECT construction gets the same round-trip safety (the coercion
    # lives in LlamaConfig.__post_init__, not just the HF builder)
    assert LlamaConfig(num_experts=2).model_type == "mixtral"
    assert LlamaConfig(num_experts=2,
                       model_type="mistral").model_type == "mixtral"
    with pytest.raises(ValueError, match="Mixtral"):
        LlamaConfig(num_experts=2, model_type="gemma")


def test_mixtral_param_structure_and_moe_every():
    """moe_every=2 places expert banks Switch-style (2nd, 4th, ...)
    while dense MLPs keep the other layers."""
    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=4,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=32, num_experts=2,
                      moe_every=2, model_type="mixtral")
    params = init_params(LlamaForCausalLM(cfg), cfg)
    bb = params["backbone"]
    assert "moe" not in bb["layers_0"] and "mlp" in bb["layers_0"]
    assert "moe" in bb["layers_1"] and "mlp" not in bb["layers_1"]
    moe = bb["layers_1"]["moe"]
    assert moe["w1"].shape == (2, 16, 32)
    assert moe["w2"].shape == (2, 32, 16)
    assert moe["w3"].shape == (2, 16, 32)
    assert moe["router"].shape == (16, 2)
    assert moe["router"].dtype == jnp.float32


def test_mixtral_aux_loss_sowed():
    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=2,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=32, num_experts=2,
                      model_type="mixtral")
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(3, 64, (2, 8)))
    _, aux = model.apply({"params": params}, ids, deterministic=True,
                         mutable=["losses"])
    flat = jax.tree.leaves(aux["losses"])
    assert len(flat) == 2                  # one sow per MoE layer
    assert all(float(v) >= 0.0 for v in flat)


def test_mixtral_pp_rejected():
    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=2,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=32, num_experts=2,
                      model_type="mixtral", pipeline_stages=2)
    with pytest.raises(ValueError, match="num_experts"):
        init_params(LlamaForCausalLM(cfg), cfg)


@pytest.mark.slow
def test_mixtral_incremental_decode_matches_full(mixtral_dir):
    """Prefill+cached decode = full-forward argmax (no capacity drops at
    the parity factor, so routing is identical across the two paths)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
    )

    model, params, _, _ = auto_models.from_pretrained(
        mixtral_dir, task="causal-lm", expert_capacity_factor=NO_DROP)
    rng = np.random.RandomState(2)
    ids = rng.randint(3, 128, (2, 6))
    new = 4
    got = np.asarray(generate_causal(model, params, ids,
                                     max_new_tokens=new))
    cur = ids.copy()
    for _ in range(new):
        logits = model.apply({"params": params}, jnp.asarray(cur),
                             deterministic=True)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    want = cur[:, ids.shape[1]:]
    for b in range(ids.shape[0]):
        row = want[b]
        eos = np.where(row == 2)[0]
        upto = (eos[0] + 1) if len(eos) else new
        np.testing.assert_array_equal(got[b, :upto], row[:upto])


@pytest.mark.slow
def test_mixtral_dp_ep_training_matches_single_device(devices8):
    """dp2×ep2×tp2 Mixtral training = single-device training: routing is
    per batch row, so sharding the batch/experts reshards the einsums
    (all-to-alls) without changing the math."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=3)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=16)

    def run(mesh_cfg, devices):
        mesh = build_mesh(mesh_cfg, devices=devices)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry")
        model_cfg = LlamaConfig(
            vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=64,
            max_position_embeddings=16, num_experts=2,
            model_type="mixtral")
        model = LlamaForCausalLM(model_cfg)
        params = init_params(model, model_cfg)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 4:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses

    single = run(MeshConfig(), devices8[:1])
    sharded = run(MeshConfig(dp=2, ep=2, tp=2), devices8)
    np.testing.assert_allclose(sharded, single, atol=3e-5)


def test_mixtral_rejected_by_speculative_and_chunked_prefill():
    """Expert capacity is a function of the apply's sequence length, so
    multi-token verify windows / prefill chunks could capacity-drop
    assignments that single-token steps (or the single-pass prefill)
    never drop — both decode accelerators reject MoE models loudly
    instead of silently breaking their token-exactness guarantees."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
        generate_speculative,
    )

    cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=2,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      max_position_embeddings=64, num_experts=2,
                      model_type="mixtral")
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg)
    dense_cfg = LlamaConfig(vocab_size=64, hidden_size=16, num_layers=1,
                            num_heads=2, num_kv_heads=2,
                            intermediate_size=32,
                            max_position_embeddings=64)
    dense = LlamaForCausalLM(dense_cfg)
    dense_params = init_params(dense, dense_cfg)
    ids = np.ones((1, 8), np.int64) * 5
    with pytest.raises(ValueError, match="capacity"):
        generate_speculative(model, params, dense, dense_params, ids)
    with pytest.raises(ValueError, match="capacity"):
        generate_speculative(dense, dense_params, model, params, ids)
    with pytest.raises(ValueError, match="capacity"):
        generate_causal(model, params, ids, prefill_chunk=4)
