"""Trainer engine tests: end-to-end learning, single-device vs 8-way DP
parity (same seed → same loss curve, SURVEY.md §4), results-file
contract, eval aggregation with padded tails."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import MeshConfig, build_mesh
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer
from huggingface_sagemaker_tensorflow_distributed_tpu.utils.results import read_results_file

SEQ = 32


def _tiny_model(seed=0, vocab=512):
    cfg = EncoderConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ)
    model = BertForSequenceClassification(cfg, num_labels=2)
    return model, init_params(model, cfg, seed=seed)


def _data(n=256, seed=0, vocab=512):
    tok = WordHashTokenizer(vocab_size=vocab)
    texts, labels = synthetic_text_classification(n, seed=seed)
    return ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)


def test_training_learns(tmp_path):
    cfg = TrainConfig(epochs=3, train_batch_size=2, dtype="float32",
                      learning_rate=1e-3, scale_lr_by_world_size=False,
                      output_data_dir=str(tmp_path), log_every_steps=0)
    mesh = build_mesh(MeshConfig())
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(_data(), 16, mesh, shuffle=True, seed=0)
    hist = trainer.fit(batcher)
    assert hist["loss"][-1] < hist["loss"][0] * 0.95
    assert hist["sparse_categorical_accuracy"][-1] > 0.8
    assert hist["train_runtime"] > 0


def test_dp8_matches_dp1_loss_curve(devices8):
    """The distributed-parity test the reference could never run without a
    cluster (SURVEY.md §4): same global batch + seed on a 1-device mesh vs
    an 8-way DP mesh must give the same loss sequence (fp32)."""
    losses = {}
    for n_dev in (1, 8):
        mesh = build_mesh(MeshConfig(), devices=devices8[:n_dev])
        cfg = TrainConfig(epochs=1, dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0)
        model, params = _tiny_model(seed=0)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(_data(n=64, seed=0), 16, mesh,
                                 shuffle=True, seed=0)
        run = []
        for batch in batcher.global_arrays(0):
            trainer.state, metrics = trainer._train_step(trainer.state, batch)
            run.append(float(jax.device_get(metrics["loss"])))
        losses[n_dev] = run
    np.testing.assert_allclose(losses[8], losses[1], atol=1e-5)


def test_lr_world_size_scaling():
    # reference semantics: lr × hvd.size() (scripts/train.py:112)
    mesh = build_mesh(MeshConfig())  # 8 devices
    cfg = TrainConfig(dtype="float32")
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    assert trainer.scaled_lr == pytest.approx(5e-5 * 8)
    cfg2 = TrainConfig(dtype="float32", scale_lr_by_world_size=False)
    trainer2 = Trainer(cfg2, model, params, mesh)
    assert trainer2.scaled_lr == pytest.approx(5e-5)


def test_eval_with_padded_tail_is_exact():
    """Eval over a non-divisible dataset must average over exactly N
    examples (padded rows masked out) — the XLA static-shape answer to
    tf.data's ragged final batch (reference train.py:98-100)."""
    mesh = build_mesh(MeshConfig())
    cfg = TrainConfig(dtype="float32", log_every_steps=0)
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    ds = _data(n=40)  # 40 % 16 = 8 → padded tail
    full = trainer.evaluate(ShardedBatcher(ds, 16, mesh, shuffle=False,
                                           drop_remainder=False))
    # brute-force reference: per-example loss over all 40, no padding
    ids = jnp.asarray(ds.columns["input_ids"])
    mask = jnp.asarray(ds.columns["attention_mask"])
    labels = jnp.asarray(ds.columns["labels"])
    logits = model.apply({"params": trainer.state.params}, ids, mask,
                         deterministic=True)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    ce = logz - jnp.take_along_axis(logits.astype(jnp.float32),
                                    labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    expected_loss = float(jnp.mean(ce))
    expected_acc = float(jnp.mean((jnp.argmax(logits, -1) == labels)))
    assert full["eval_loss"] == pytest.approx(expected_loss, abs=1e-5)
    assert full["eval_accuracy"] == pytest.approx(expected_acc, abs=1e-6)


def test_results_files_contract(tmp_path):
    """train_results.txt / eval_results.txt key = value emission
    (reference train.py:157-179)."""
    cfg = TrainConfig(epochs=1, dtype="float32", learning_rate=1e-3,
                      output_data_dir=str(tmp_path), log_every_steps=0)
    mesh = build_mesh(MeshConfig())
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(_data(n=64), 16, mesh, seed=0)
    hist = trainer.fit(batcher)
    trainer.write_train_results(hist)
    trainer.write_eval_results(trainer.evaluate(
        ShardedBatcher(_data(n=32, seed=5), 16, mesh, shuffle=False,
                       drop_remainder=False)))
    train_res = read_results_file(str(tmp_path / "train_results.txt"))
    assert "loss" in train_res and "train_runtime" in train_res
    assert "train_samples_per_second_per_chip" in train_res
    eval_res = read_results_file(str(tmp_path / "eval_results.txt"))
    assert "eval_loss" in eval_res and "eval_accuracy" in eval_res


def test_bf16_compute_runs():
    mesh = build_mesh(MeshConfig())
    cfg = TrainConfig(dtype="bfloat16", log_every_steps=0)
    mcfg = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=1,
                         num_heads=2, intermediate_size=64,
                         max_position_embeddings=SEQ, dtype=jnp.bfloat16)
    model = BertForSequenceClassification(mcfg, num_labels=2)
    params = init_params(model, mcfg)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(_data(n=32), 16, mesh, seed=0)
    batch = next(batcher.global_arrays(0))
    trainer.state, metrics = trainer._train_step(trainer.state, batch)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    # params stay fp32 (param_dtype) under bf16 compute
    leaf = jax.tree.leaves(trainer.state.params)[0]
    assert leaf.dtype == jnp.float32


def test_bf16_training_quality_matches_fp32(tmp_path):
    """SURVEY.md §7 hard-part 5: bf16 matmuls with fp32 params, layernorm
    statistics, softmax, and loss must train to the same quality as pure
    fp32 — the mixed-precision discipline is the claim, this is the
    evidence. Same data, same seeds, only the compute dtype differs."""

    def run(dtype):
        cfg = TrainConfig(epochs=3, train_batch_size=2, dtype=dtype,
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          output_data_dir=str(tmp_path), log_every_steps=0)
        mcfg = EncoderConfig(
            vocab_size=512, hidden_size=32, num_layers=2, num_heads=2,
            intermediate_size=64, max_position_embeddings=SEQ,
            dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
        mesh = build_mesh(MeshConfig())
        model = BertForSequenceClassification(mcfg, num_labels=2)
        trainer = Trainer(cfg, model, init_params(model, mcfg, seed=0), mesh)
        batcher = ShardedBatcher(_data(), 16, mesh, shuffle=True, seed=0)
        hist = trainer.fit(batcher)
        ev = trainer.evaluate(ShardedBatcher(_data(n=64, seed=5), 16, mesh,
                                             shuffle=False,
                                             drop_remainder=False))
        return hist, ev

    hist16, ev16 = run("bfloat16")
    hist32, ev32 = run("float32")
    # both reach the fp32 learning bar…
    assert hist16["sparse_categorical_accuracy"][-1] > 0.8
    # …and end-of-training quality agrees within 2 points (train) /
    # 3 points (held-out eval)
    assert abs(hist16["sparse_categorical_accuracy"][-1]
               - hist32["sparse_categorical_accuracy"][-1]) < 0.02
    assert abs(ev16["eval_accuracy"] - ev32["eval_accuracy"]) < 0.03
    assert abs(ev16["eval_loss"] - ev32["eval_loss"]) < 0.1


def test_gradient_accumulation_matches_big_batch():
    """accum=2 at global batch 8 must produce the same parameters as one
    step at global batch 16 (MultiSteps averages micro-grads; fp32)."""
    data = _data(n=64, seed=7)
    final = {}

    def dropout_free_model(seed=0):
        cfg = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_position_embeddings=SEQ,
                            hidden_dropout=0.0, attention_dropout=0.0)
        model = BertForSequenceClassification(cfg, num_labels=2)
        return model, init_params(model, cfg, seed=seed)

    for accum, gb in ((1, 16), (2, 8)):
        mesh = build_mesh(MeshConfig())
        cfg = TrainConfig(epochs=1, dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          gradient_accumulation_steps=accum)
        model, params = dropout_free_model(seed=0)
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(data, gb, mesh, shuffle=False, seed=0)
        for batch in batcher.global_arrays(0):
            trainer.state, _ = trainer._train_step(trainer.state, batch)
        final[accum] = jax.device_get(trainer.state.params)
    a = jax.tree.leaves(final[1])
    b = jax.tree.leaves(final[2])
    for x, y in zip(a, b):
        # fp32 mean-of-means vs one mean: reduction-order noise only
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("optimizer,lr,wd", [("adafactor", 8e-2, 0.0),
                                             ("lamb", 2e-2, 0.01)])
def test_alternative_optimizers_learn(devices8, optimizer, lr, wd):
    """Adafactor (T5's pretraining optimizer) and LAMB (large-batch
    BERT) both drive the loss down through the same trainer."""
    ds = _data(n=128)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    model, params = _tiny_model()
    # both optimizers rescale the raw lr (Adafactor by parameter scale —
    # tiny init norms mean tiny steps — LAMB by trust ratio), so the
    # tiny model needs a hotter lr / more updates than adam
    cfg = TrainConfig(dtype="float32", learning_rate=lr,
                      optimizer=optimizer, weight_decay=wd,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=8)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.9


def test_cosine_schedule_builds(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.optim import (
        build_optimizer,
    )

    cfg = TrainConfig(dtype="float32", warmup_ratio=0.1, lr_schedule="cosine")
    tx, lr = build_optimizer(cfg, world_size=1, total_steps=100)
    assert lr == cfg.learning_rate


@pytest.mark.slow
def test_eval_each_epoch_and_keep_best(devices8, monkeypatch):
    """--eval_each_epoch lands eval_loss/eval_accuracy per epoch in the
    history; --keep_best snapshots the best epoch's params and
    export_params serves THAT snapshot, not the final state (HF
    load_best_model_at_end). Scripted eval metrics force the best epoch
    to be the middle one."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        Trainer,
    )

    mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
    cfg = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ)
    model = BertForSequenceClassification(cfg, num_labels=2)
    tcfg = TrainConfig(task="seq-cls", dtype="float32", learning_rate=1e-3,
                       scale_lr_by_world_size=False, log_every_steps=0,
                       rng_impl="threefry", epochs=3, keep_best=True)
    assert tcfg.eval_each_epoch          # keep_best implies it
    trainer = Trainer(tcfg, model, init_params(model, cfg, seed=0), mesh)

    scripted = iter([0.5, 0.2, 0.9])
    captured = {}

    def fake_evaluate(batcher):
        loss = next(scripted)
        captured[loss] = jax.device_get(trainer.state.params)
        return {"eval_loss": loss, "eval_accuracy": 1.0 - loss}

    monkeypatch.setattr(trainer, "evaluate", fake_evaluate)
    data = _data(n=64, seed=3)
    hist = trainer.fit(ShardedBatcher(data, 16, mesh, shuffle=True, seed=0),
                       eval_batcher=object())
    assert hist["eval_loss"] == [0.5, 0.2, 0.9]
    assert hist["eval_accuracy"] == [0.5, 0.8, pytest.approx(0.1)]
    assert trainer.best_epoch == 1
    # the epoch-1 snapshot differs from the last epoch's weights...
    best, last = captured[0.2], captured[0.9]
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(jax.tree.leaves(best), jax.tree.leaves(last)))
    # ...and fit() restored it into the LIVE state (load_best_model_at
    # _end), so the final eval, export and task-metric passes all see
    # the best model
    for a, b in zip(jax.tree.leaves(best),
                    jax.tree.leaves(jax.device_get(trainer.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(best),
                    jax.tree.leaves(jax.device_get(trainer.export_params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_early_stopping_patience(devices8, monkeypatch):
    """Training stops after `patience` epochs without improvement on the
    watched metric; with --keep_best the best snapshot still wins."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        Trainer,
    )

    mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
    cfg = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ)
    model = BertForSequenceClassification(cfg, num_labels=2)
    tcfg = TrainConfig(task="seq-cls", dtype="float32", learning_rate=1e-3,
                       scale_lr_by_world_size=False, log_every_steps=0,
                       rng_impl="threefry", epochs=10, keep_best=True,
                       early_stopping_patience=2)
    trainer = Trainer(tcfg, model, init_params(model, cfg, seed=0), mesh)

    scripted = iter([0.5, 0.2, 0.4, 0.3, 0.9, 0.9, 0.9, 0.9, 0.9, 0.9])
    monkeypatch.setattr(
        trainer, "evaluate",
        lambda b: (lambda l: {"eval_loss": l, "eval_accuracy": 1 - l})(
            next(scripted)))
    data = _data(n=64, seed=3)
    hist = trainer.fit(ShardedBatcher(data, 16, mesh, shuffle=True, seed=0),
                       eval_batcher=object())
    # best at epoch 1 (0.2); epochs 2 and 3 don't improve → stop after 3
    assert hist["eval_loss"] == [0.5, 0.2, 0.4, 0.3]
    assert trainer.best_epoch == 1
    assert len(hist["loss"]) == 4
