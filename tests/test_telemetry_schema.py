"""Schema-drift gate: ``scripts/check_telemetry_schema.py`` (pure
stdlib, runs without jax) must accept what the live emitters write and
reject drifted/corrupt artifacts — so any change to the event schema
that forgets the validator (or vice versa) fails tier-1 fast.
"""

import json
import os
import subprocess
import sys

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "check_telemetry_schema.py")


def _run(*paths, extra=()):
    return subprocess.run([sys.executable, _SCRIPT, *extra, *paths],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, cwd=_REPO)


@pytest.fixture()
def artifacts(tmp_path):
    """Real artifacts from the real emitters — the round-trip the
    validator must bless."""
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        with obs.span("train/step_dispatch"):
            with obs.span("data/next_batch"):
                pass
        obs.scalar("train/loss", 1.25, 7)
        obs.state().events.emit("compile", {
            "event": "/jax/pjit/compile", "dur": 1.0, "count": 1,
            "cum": 1.0})
        obs.flush()
    finally:
        obs.reset()
    return out


def test_validator_accepts_live_emitter_output(artifacts):
    proc = _run(str(artifacts / "events.jsonl"),
                str(artifacts / "trace.json"))
    assert proc.returncode == 0, proc.stdout
    assert proc.stdout.count("OK") == 2


def test_validator_accepts_directory_form(artifacts):
    proc = _run(str(artifacts))
    assert proc.returncode == 0, proc.stdout


def test_validator_runs_without_jax(artifacts):
    """The pure-stdlib contract, enforced: jax import is poisoned."""
    code = ("import sys, runpy; sys.modules['jax'] = None; "
            "sys.argv = ['x', %r]; "
            "runpy.run_path(%r, run_name='__main__')"
            % (str(artifacts), _SCRIPT))
    proc = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, proc.stdout


def test_validator_rejects_drifted_events(tmp_path):
    bad = tmp_path / "events.jsonl"
    rows = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
         "name": "ok", "value": 1.0},
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
         "value": 2.0},                                   # missing name
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "wat"},  # bad type
    ]
    bad.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "missing field 'name'" in proc.stdout
    assert "unknown event type" in proc.stdout


def test_validator_rejects_empty_artifact(tmp_path):
    empty = tmp_path / "events.jsonl"
    empty.write_text("")
    proc = _run(str(empty))
    assert proc.returncode == 1
    assert "empty artifact" in proc.stdout


def test_validator_tolerates_torn_tail_not_middle(tmp_path):
    ok = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
          "name": "a", "value": 1.0}
    torn_tail = tmp_path / "tail.jsonl"
    torn_tail.write_text(json.dumps(ok) + '\n{"v": 1, "t": 9')
    assert _run(str(torn_tail)).returncode == 0
    assert _run(str(torn_tail), extra=("--strict-tail",)).returncode == 1
    torn_mid = tmp_path / "mid.jsonl"
    torn_mid.write_text('{"v": 1, "t...\n' + json.dumps(ok) + "\n")
    assert _run(str(torn_mid)).returncode == 1


def test_validator_rejects_bad_trace(tmp_path):
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "a", "ph": "X", "ts": 1.0, "pid": 0, "tid": 1},  # no dur
    ]}))
    proc = _run(str(trace))
    assert proc.returncode == 1
    assert "without numeric 'dur'" in proc.stdout
