"""Schema-drift gate: ``scripts/check_telemetry_schema.py`` (pure
stdlib, runs without jax) must accept what the live emitters write and
reject drifted/corrupt artifacts — so any change to the event schema
that forgets the validator (or vice versa) fails tier-1 fast.
"""

import json
import os
import subprocess
import sys

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "check_telemetry_schema.py")


def _run(*paths, extra=()):
    return subprocess.run([sys.executable, _SCRIPT, *extra, *paths],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, cwd=_REPO)


@pytest.fixture()
def artifacts(tmp_path):
    """Real artifacts from the real emitters — the round-trip the
    validator must bless."""
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        with obs.span("train/step_dispatch"):
            with obs.span("data/next_batch"):
                pass
        obs.scalar("train/loss", 1.25, 7)
        obs.state().events.emit("compile", {
            "event": "/jax/pjit/compile", "dur": 1.0, "count": 1,
            "cum": 1.0})
        obs.flush()
    finally:
        obs.reset()
    return out


def test_validator_accepts_live_emitter_output(artifacts):
    proc = _run(str(artifacts / "events.jsonl"),
                str(artifacts / "trace.json"))
    assert proc.returncode == 0, proc.stdout
    assert proc.stdout.count("OK") == 2


def test_validator_accepts_directory_form(artifacts):
    proc = _run(str(artifacts))
    assert proc.returncode == 0, proc.stdout


def test_validator_runs_without_jax(artifacts):
    """The pure-stdlib contract, enforced at RUNTIME: jax import is
    poisoned in a subprocess. Since ISSUE 15 the PRIMARY no-jax gate
    is graftlint R1's static import reachability (tier-1, complete
    over all import edges); this subprocess run is the one retained
    slow-tier backstop covering what static analysis can't — e.g. an
    import-hook or __getattr__ that only misbehaves when executed."""
    code = ("import sys, runpy; sys.modules['jax'] = None; "
            "sys.argv = ['x', %r]; "
            "runpy.run_path(%r, run_name='__main__')"
            % (str(artifacts), _SCRIPT))
    proc = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, proc.stdout


def test_validator_rejects_drifted_events(tmp_path):
    bad = tmp_path / "events.jsonl"
    rows = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
         "name": "ok", "value": 1.0},
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
         "value": 2.0},                                   # missing name
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "wat"},  # bad type
    ]
    bad.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "missing field 'name'" in proc.stdout
    assert "unknown event type" in proc.stdout


def test_validator_rejects_empty_artifact(tmp_path):
    empty = tmp_path / "events.jsonl"
    empty.write_text("")
    proc = _run(str(empty))
    assert proc.returncode == 1
    assert "empty artifact" in proc.stdout


def test_validator_tolerates_torn_tail_not_middle(tmp_path):
    ok = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "metric",
          "name": "a", "value": 1.0}
    torn_tail = tmp_path / "tail.jsonl"
    torn_tail.write_text(json.dumps(ok) + '\n{"v": 1, "t": 9')
    assert _run(str(torn_tail)).returncode == 0
    assert _run(str(torn_tail), extra=("--strict-tail",)).returncode == 1
    torn_mid = tmp_path / "mid.jsonl"
    torn_mid.write_text('{"v": 1, "t...\n' + json.dumps(ok) + "\n")
    assert _run(str(torn_mid)).returncode == 1


def test_produced_train_and_serve_artifacts_validate(tmp_path):
    """The drift gate the hand-built fixtures can't provide: run a REAL
    tiny instrumented train + serve step and push the PRODUCED
    events.jsonl through the validator script end-to-end — a new event
    type (like PR 3's ``serve``) that forgets the schema, or a schema
    change that forgets an emitter, fails here fast."""
    import numpy as np

    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        from tests.test_trainer import _data, _tiny_model
        from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
            TrainConfig,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
            ShardedBatcher,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
            init_params,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
            Gpt2Config,
            Gpt2LMHeadModel,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
            MeshConfig,
            build_mesh,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
            ServeEngine,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.train import (
            Trainer,
        )

        cfg = TrainConfig(epochs=1, train_batch_size=2, dtype="float32",
                          scale_lr_by_world_size=False,
                          output_data_dir=str(tmp_path), log_every_steps=2)
        mesh = build_mesh(MeshConfig())
        model, params = _tiny_model()
        Trainer(cfg, model, params, mesh).fit(
            ShardedBatcher(_data(n=32), 16, mesh, shuffle=False, seed=0))

        gcfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position_embeddings=64, hidden_dropout=0.0,
                          embd_dropout=0.0, attention_dropout=0.0,
                          eos_token_id=127, pad_token_id=0)
        gmodel = Gpt2LMHeadModel(gcfg)
        # a SPECULATIVE engine (ISSUE 6): the produced stream must
        # carry the acceptance-rate fields on finish/report events —
        # fixtures regenerated from a real speculative run, not
        # hand-built
        eng = ServeEngine(gmodel, init_params(gmodel, gcfg, seed=0),
                          num_slots=2, block_size=8, num_blocks=17,
                          prefill_chunk=8, max_model_len=32,
                          speculate_k=2, draft=1)
        eng.submit(np.arange(1, 6, dtype=np.int32), 4)
        # one sampled request so the produced stream carries the
        # ISSUE 5 serve fields (submit.sampled True alongside False)
        eng.submit(np.arange(2, 10, dtype=np.int32), 3,
                   temperature=0.8, top_k=8, seed=1)
        eng.run()
        obs.flush()
        events = [e for _, e, err in obs.iter_events(
            str(out / "events.jsonl")) if err is None]
    finally:
        obs.reset()
    types = {e["type"] for e in events}
    # both subsystems actually emitted (an empty gate proves nothing)
    assert {"metric", "span", "serve"} <= types
    serve = [e for e in events if e["type"] == "serve"]
    serve_events = {e.get("event") for e in serve}
    assert {"submit", "first_token", "finish", "report",
            "bucket_switch"} <= serve_events
    # the typed optional fields ride the real stream: every submit
    # carries the sampling flag (both modes), every bucket_switch the
    # bucket width — regenerated-from-live fixtures, not hand-built
    submits = [e for e in serve if e["event"] == "submit"]
    assert {e["sampled"] for e in submits} == {True, False}
    assert all(isinstance(e["gather_bucket"], int) for e in serve
               if e["event"] == "bucket_switch")
    # the ISSUE 6 acceptance telemetry rides the live stream typed:
    # every finish carries the per-request rate, the report the
    # aggregate + speculate_k
    finishes = [e for e in serve if e["event"] == "finish"]
    assert finishes and all(
        isinstance(e["acceptance_rate"], (int, float))
        and isinstance(e["draft_proposed"], int) for e in finishes)
    report = [e for e in serve if e["event"] == "report"][-1]
    assert report["speculate_k"] == 2
    assert isinstance(report["acceptance_rate"], (int, float))
    # the ISSUE 10 lifecycle tracing rides the live stream typed (the
    # engine's timeline defaults ON): every finished request emitted a
    # request_timeline whose decomposition fields and segment list are
    # real, and every iteration an iteration_ledger — fixtures
    # regenerated from this live speculative run, not hand-built
    timelines = [e for e in serve if e["event"] == "request_timeline"]
    assert timelines and {e["at"] for e in timelines} >= {"finish"}
    assert len([e for e in timelines if e["at"] == "finish"]) \
        == len(finishes)
    for e in timelines:
        assert isinstance(e["e2e_s"], (int, float))
        assert isinstance(e["segments"], list) and e["segments"]
        for ph in ("queue", "prefill", "decode", "preempted",
                   "overhead"):
            assert isinstance(e[f"{ph}_s"], (int, float))
    ledgers = [e for e in serve if e["event"] == "iteration_ledger"]
    assert ledgers and all(
        isinstance(e["iteration"], int)
        and isinstance(e["dur_s"], (int, float))
        and isinstance(e["gather_bucket"], int)
        and isinstance(e["kv_used_frac"], (int, float))
        for e in ledgers)
    # the report event carries the timeline-gated SLO aggregates
    assert isinstance(report["queue_wait_p99_s"], (int, float))
    assert isinstance(report["decode_time_frac"], (int, float))
    proc = _run(str(out))
    assert proc.returncode == 0, proc.stdout
    assert proc.stdout.count("OK") == 2          # events.jsonl + trace.json


def test_produced_router_artifacts_validate(tmp_path):
    """ISSUE 14 fixture regeneration from a REAL 2-replica router run
    (a forced mid-trace drain included): the produced stream must
    carry the replica tag typed on every per-request lifecycle event,
    the drain/requeue events, the per-replica reports plus the
    aggregate router report (placement / imbalance / per_replica), and
    pass the validator end to end — fixtures from live emitters, not
    hand-built."""
    import numpy as np

    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
            init_params,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
            Gpt2Config,
            Gpt2LMHeadModel,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
            Router,
        )

        gcfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position_embeddings=64, hidden_dropout=0.0,
                          embd_dropout=0.0, attention_dropout=0.0,
                          eos_token_id=127, pad_token_id=0)
        gmodel = Gpt2LMHeadModel(gcfg)
        router = Router(gmodel, init_params(gmodel, gcfg, seed=0),
                        replicas=2, placement="round_robin",
                        num_slots=1, block_size=8, num_blocks=17,
                        prefill_chunk=8, max_model_len=32)
        rng = np.random.RandomState(0)
        for _ in range(6):
            router.submit(rng.randint(1, 120, (5,)).astype(np.int32), 4)
        router.warmup()
        router.step()                    # admit 1 per replica...
        moved = router.drain(0)          # ...then requeue 0's waiting
        assert moved, "drain must move waiting requests"
        router.run()
        obs.flush()
        events = [e for _, e, err in obs.iter_events(
            str(out / "events.jsonl")) if err is None]
    finally:
        obs.reset()
    serve = [e for e in events if e["type"] == "serve"]
    kinds = {e.get("event") for e in serve}
    assert {"submit", "finish", "request_timeline", "drain",
            "requeue", "report"} <= kinds
    for kind in ("submit", "admit", "first_token", "finish",
                 "request_timeline", "iteration_ledger"):
        rows = [e for e in serve if e.get("event") == kind]
        assert rows and all(isinstance(e["replica"], int)
                            for e in rows), kind
    drains = [e for e in serve if e["event"] == "drain"]
    assert drains and all(isinstance(e["requeued"], int)
                          and isinstance(e["placement"], str)
                          for e in drains)
    requeues = [e for e in serve if e["event"] == "requeue"]
    assert requeues and all(
        isinstance(e["replica"], int) and isinstance(e["to_replica"], int)
        for e in requeues)
    reports = [e for e in serve if e["event"] == "report"]
    agg = reports[-1]
    assert agg["replicas"] == 2 and isinstance(agg["placement"], str)
    assert isinstance(agg["replica_load_imbalance"], (int, float))
    assert isinstance(agg["per_replica"], list)
    assert isinstance(agg["drains"], int) and agg["drains"] == 1
    proc = _run(str(out))
    assert proc.returncode == 0, proc.stdout


def test_produced_open_loop_artifacts_validate(tmp_path):
    """ISSUE 16 fixture regeneration from a REAL wall-clock open-loop
    run (Poisson arrivals + generous SLOs on a tiny engine): the
    produced stream must lead with the driver's ``open_loop`` stamp,
    carry arrival/SLO-target riders typed on submits, per-request
    verdicts on finishes, the attainment aggregate + backlog peak on
    the report, and pass the validator end to end — fixtures from live
    emitters, not hand-built."""
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
            init_params,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
            Gpt2Config,
            Gpt2LMHeadModel,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
            ServeEngine,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.loadgen import (
            OpenLoopDriver,
            SloSpec,
            make_schedule,
        )

        gcfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position_embeddings=64, hidden_dropout=0.0,
                          embd_dropout=0.0, attention_dropout=0.0,
                          eos_token_id=127, pad_token_id=0)
        gmodel = Gpt2LMHeadModel(gcfg)
        eng = ServeEngine(gmodel, init_params(gmodel, gcfg, seed=0),
                          num_slots=2, block_size=8, num_blocks=17,
                          prefill_chunk=8, max_model_len=32)
        drv = OpenLoopDriver(
            eng,
            make_schedule(5, 128, process="poisson", rate=100.0, seed=2,
                          prompt_lo=4, prompt_hi=8, new_lo=3, new_hi=5,
                          eos_token_id=127, groups=("a", "b")),
            clock="wall", slo=SloSpec(ttft_s=10.0, tpot_s=10.0),
            process="poisson", rate=100.0)
        drv.run()
        obs.flush()
        events = [e for _, e, err in obs.iter_events(
            str(out / "events.jsonl")) if err is None]
    finally:
        obs.reset()
    serve = [e for e in events if e["type"] == "serve"]
    kinds = [e.get("event") for e in serve]
    assert kinds.index("open_loop") < kinds.index("submit")
    stamp = next(e for e in serve if e["event"] == "open_loop")
    assert stamp["process"] == "poisson" and stamp["clock"] == "wall"
    assert stamp["requests"] == 5 and isinstance(stamp["rate"], float)
    assert isinstance(stamp["slo_ttft_s"], (int, float))
    submits = [e for e in serve if e["event"] == "submit"]
    assert len(submits) == 5 and all(
        isinstance(e["arrival_s"], (int, float))
        and isinstance(e["slo_ttft_s"], (int, float))
        and isinstance(e["slo_tpot_s"], (int, float)) for e in submits)
    finishes = [e for e in serve if e["event"] == "finish"]
    assert len(finishes) == 5 and all(
        isinstance(e["slo_met"], bool)
        and isinstance(e["ttft_slo_met"], bool)
        and isinstance(e["tpot_slo_met"], bool)
        and isinstance(e["slack_s"], (int, float)) for e in finishes)
    report = [e for e in serve if e["event"] == "report"][-1]
    assert report["slo_attainment"] == 1.0       # generous targets
    assert isinstance(report["arrival_backlog_peak"], int)
    assert isinstance(report["group_slo_attainment"], dict)
    ledgers = [e for e in serve if e["event"] == "iteration_ledger"]
    assert ledgers and all(isinstance(e["arrival_backlog"], int)
                           for e in ledgers)
    proc = _run(str(out))
    assert proc.returncode == 0, proc.stdout


def test_produced_swap_artifacts_validate(tmp_path):
    """ISSUE 17 fixture regeneration from a REAL forced-swap run (a
    pool far too small for the resident requests, ``swap='always'``):
    the produced stream must carry per-victim ``swap_out`` /
    ``swap_in`` events typed (bytes moved; the restore additionally
    its scatter seconds and the re-prefill tokens it avoided), the
    report event the run aggregates the ``obsctl diff`` gates read,
    and pass the validator end to end — fixtures from live emitters,
    not hand-built."""
    import numpy as np

    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
            init_params,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
            Gpt2Config,
            Gpt2LMHeadModel,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
            ServeEngine,
        )

        gcfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64,
                          max_position_embeddings=64, hidden_dropout=0.0,
                          embd_dropout=0.0, attention_dropout=0.0,
                          eos_token_id=127, pad_token_id=0)
        gmodel = Gpt2LMHeadModel(gcfg)
        # 5 requests of up to 27 tokens against 9 usable 4-token
        # blocks: the scheduler MUST preempt, and swap='always' turns
        # every preemption into a host round-trip
        eng = ServeEngine(gmodel, init_params(gmodel, gcfg, seed=0),
                          num_slots=4, block_size=4, num_blocks=10,
                          prefill_chunk=8, max_model_len=32,
                          prefix_cache=True, swap="always")
        for i in range(5):
            eng.submit(np.arange(1 + i, 10 + i, dtype=np.int32), 18)
        eng.run()
        obs.flush()
        events = [e for _, e, err in obs.iter_events(
            str(out / "events.jsonl")) if err is None]
    finally:
        obs.reset()
    serve = [e for e in events if e["type"] == "serve"]
    swap_outs = [e for e in serve if e.get("event") == "swap_out"]
    swap_ins = [e for e in serve if e.get("event") == "swap_in"]
    # the run really swapped, and every transfer event is typed
    assert swap_outs and swap_ins
    assert all(isinstance(e["swap_bytes"], int) and e["swap_bytes"] > 0
               for e in swap_outs + swap_ins)
    assert all(isinstance(e["restore_s"], (int, float))
               and isinstance(e["recompute_tokens_avoided"], int)
               for e in swap_ins)
    # the report event carries the aggregates `obsctl diff` gates
    report = [e for e in serve if e.get("event") == "report"][-1]
    assert report["swap_policy"] == "always"
    assert isinstance(report["swap_outs"], int) and report["swap_outs"] > 0
    assert isinstance(report["swap_ins"], int) and report["swap_ins"] > 0
    assert isinstance(report["swap_bytes"], int) and report["swap_bytes"] > 0
    assert isinstance(report["restore_s"], (int, float))
    assert isinstance(report["recompute_tokens_avoided"], int)
    assert isinstance(report["host_tier_hits"], int)
    assert isinstance(report["host_tier_hit_rate"], (int, float))
    proc = _run(str(out))
    assert proc.returncode == 0, proc.stdout


def test_validator_rejects_mistyped_open_loop_fields(tmp_path):
    """ISSUE 16 deadline fields: optional on `serve` events but TYPED
    when present — a drifted emitter (string verdict, float backlog)
    fails the gate instead of silently poisoning goodput replay. Own
    file: the validator caps printed errors per artifact and these
    rows would fall past the serve-fields file's cap."""
    bad = tmp_path / "events.jsonl"
    rows = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "open_loop", "process": "poisson", "clock": "wall",
         "rate": 8.0, "requests": 16, "slo_ttft_s": 0.1,
         "slo_tpot_s": 0.05},                                    # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "open_loop", "process": 7, "clock": True,
         "rate": "fast", "requests": 2.5},                       # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "submit", "request": 0, "arrival_s": 0.25,
         "slo_ttft_s": 0.1},                                     # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "submit", "request": 1, "arrival_s": "soon",
         "slo_ttft_s": "tight", "slo_tpot_s": False},            # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 0, "slo_met": True,
         "ttft_slo_met": True, "tpot_slo_met": True,
         "slack_s": 0.04},                                       # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 1, "slo_met": 1,
         "ttft_slo_met": "yes", "tpot_slo_met": 0.5,
         "slack_s": "none"},                                     # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "iteration_ledger", "iteration": 3, "dur_s": 0.01,
         "arrival_backlog": 4},                                  # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "iteration_ledger", "iteration": 4, "dur_s": 0.01,
         "arrival_backlog": 4.5},                                # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "slo_attainment": 0.97,
         "group_slo_attainment": {"a": 1.0},
         "arrival_backlog_peak": 6},                             # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "slo_attainment": "high",
         "group_slo_attainment": [1.0],
         "arrival_backlog_peak": "deep"},                        # drift
    ]
    bad.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    for field in ("process", "clock", "rate", "requests", "arrival_s",
                  "slo_ttft_s", "slo_tpot_s", "slo_met", "ttft_slo_met",
                  "tpot_slo_met", "slack_s", "arrival_backlog",
                  "slo_attainment", "group_slo_attainment",
                  "arrival_backlog_peak"):
        assert f"optional field '{field}'" in proc.stdout, field


def test_validator_rejects_mistyped_serve_optional_fields(tmp_path):
    """gather_bucket/sampled are optional on `serve` events but TYPED
    when present — a drifted emitter (string bucket, int flag) fails
    the gate instead of poisoning downstream bucket accounting."""
    bad = tmp_path / "events.jsonl"
    rows = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "bucket_switch", "gather_bucket": 128},       # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "bucket_switch", "gather_bucket": "wide"},    # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "submit", "request": 0, "sampled": 1},        # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 0,
         "acceptance_rate": 0.75, "draft_proposed": 8},         # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 1,
         "acceptance_rate": "high", "speculate_k": 2.5},        # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 2,
         "prefix_cached_tokens": 96, "cache_hit_rate": 0.92},   # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 3,
         "prefix_cached_tokens": 96.5, "cache_hit_rate": "hot"},  # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 4,
         "kernel": "pallas", "kv_dtype": "int8",
         "kv_bytes_read": 4096},                                 # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "kernel": 1, "kv_dtype": False,
         "kv_bytes_read_per_step": "lots"},                      # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "request_timeline", "request": 5, "at": "finish",
         "e2e_s": 1.5, "queue_s": 0.5, "prefill_s": 0.1,
         "decode_s": 0.8, "preempted_s": 0.0, "overhead_s": 0.1,
         "segments": [{"ph": "queue", "t0": 0.0, "dur": 0.5}],
         "group": "tenant0", "blocked_iters": 3,
         "blocked_reason": "kv_capacity"},                       # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "request_timeline", "request": 6, "at": 2,
         "e2e_s": "slow", "queue_s": True, "segments": "none",
         "group": 7, "blocked_reason": 1},                       # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "iteration_ledger", "iteration": 4, "dur_s": 0.02,
         "gather_bucket": 64, "decode_slots": 3, "waiting": 1,
         "kv_used_frac": 0.4},                                   # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "iteration_ledger", "iteration": 4.5,
         "dur_s": "fast", "decode_slots": 3.1, "waiting": "deep",
         "kv_used_frac": "full"},                                # drift
    ]
    bad.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "optional field 'gather_bucket'" in proc.stdout
    assert "optional field 'sampled'" in proc.stdout
    assert "optional field 'acceptance_rate'" in proc.stdout
    assert "optional field 'speculate_k'" in proc.stdout
    assert "optional field 'prefix_cached_tokens'" in proc.stdout
    assert "optional field 'cache_hit_rate'" in proc.stdout
    assert "optional field 'kernel'" in proc.stdout
    assert "optional field 'kv_dtype'" in proc.stdout
    assert "optional field 'kv_bytes_read_per_step'" in proc.stdout
    # ISSUE 10 lifecycle-tracing fields: typed when present, so a
    # drifted emitter can't poison obsctl timeline/slo/tail silently
    assert "optional field 'at'" in proc.stdout
    assert "optional field 'e2e_s'" in proc.stdout
    assert "optional field 'queue_s'" in proc.stdout
    assert "optional field 'segments'" in proc.stdout
    assert "optional field 'group'" in proc.stdout
    assert "optional field 'blocked_reason'" in proc.stdout
    assert "optional field 'iteration'" in proc.stdout
    # ISSUE 14 multi-replica router fields: typed when present, so a
    # drifted emitter can't poison per-replica attribution silently
    # (own file — the validator caps printed errors per artifact, and
    # the router rows would fall past the first file's cap)
    bad2 = tmp_path / "router_events.jsonl"
    rows2 = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "drain", "replica": 1, "requeued": 3,
         "placement": "affinity"},                               # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "requeue", "request": 7, "replica": 1,
         "to_replica": 0},                                       # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "drain", "replica": "one", "requeued": "many",
         "placement": 3},                                        # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "replicas": 2, "placement": "round_robin",
         "replica_load_imbalance": 1.1,
         "per_replica": [{"replica": 0}]},                       # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "replicas": 2.5, "to_replica": "zero",
         "replica_load_imbalance": "even", "per_replica": "all"},  # drift
    ]
    bad2.write_text("\n".join(json.dumps(r) for r in rows2) + "\n")
    proc2 = _run(str(bad2))
    assert proc2.returncode == 1
    assert "optional field 'replica'" in proc2.stdout
    assert "optional field 'requeued'" in proc2.stdout
    assert "optional field 'placement'" in proc2.stdout
    assert "optional field 'replicas'" in proc2.stdout
    assert "optional field 'to_replica'" in proc2.stdout
    assert "optional field 'replica_load_imbalance'" in proc2.stdout
    assert "optional field 'per_replica'" in proc2.stdout
    assert "optional field 'dur_s'" in proc.stdout
    assert "optional field 'decode_slots'" in proc.stdout
    assert "optional field 'waiting'" in proc.stdout
    assert "optional field 'kv_used_frac'" in proc.stdout
    # ISSUE 17 host-RAM KV tier fields: typed when present, so a
    # drifted emitter can't poison the swap-traffic / tier-hit
    # accounting `obsctl diff` gates (own file — same error-cap
    # reasoning as the router rows)
    bad3 = tmp_path / "swap_events.jsonl"
    rows3 = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "swap_out", "request": 3,
         "swap_bytes": 1 << 16},                                 # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "swap_in", "request": 3, "swap_bytes": 1 << 16,
         "restore_s": 0.01, "recompute_tokens_avoided": 120},    # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "swap_out", "request": 4,
         "swap_bytes": "heavy"},                                 # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "swap_in", "request": 4, "restore_s": "fast",
         "recompute_tokens_avoided": 9.5},                       # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "swap_policy": "auto", "swap_outs": 2,
         "swap_ins": 2, "host_tier_hits": 8,
         "host_tier_hit_rate": 0.8},                             # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "swap_policy": True, "swap_outs": 2.5,
         "swap_ins": "both", "host_tier_hits": "some",
         "host_tier_hit_rate": "warm"},                          # drift
    ]
    bad3.write_text("\n".join(json.dumps(r) for r in rows3) + "\n")
    proc3 = _run(str(bad3))
    assert proc3.returncode == 1
    assert "optional field 'swap_bytes'" in proc3.stdout
    assert "optional field 'restore_s'" in proc3.stdout
    assert "optional field 'recompute_tokens_avoided'" in proc3.stdout
    assert "optional field 'swap_policy'" in proc3.stdout
    assert "optional field 'swap_outs'" in proc3.stdout
    assert "optional field 'swap_ins'" in proc3.stdout
    assert "optional field 'host_tier_hits'" in proc3.stdout
    assert "optional field 'host_tier_hit_rate'" in proc3.stdout
    # ISSUE 18 cross-engine transport fields: the migrate event and
    # the fleet report riders are typed when present, so a drifted
    # emitter can't poison the migration-traffic / disagg-attainment
    # accounting `obsctl diff` gates
    bad4 = tmp_path / "migrate_events.jsonl"
    rows4 = [
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "migrate", "request": 3, "from_replica": 0,
         "to_replica": 1, "migration_bytes": 1 << 16,
         "restore_s": 0.01},                                     # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "migrate", "request": 4, "from_replica": "zero",
         "migration_bytes": "heavy"},                            # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "drain", "replica": 0, "requeued": 2,
         "migrated": 3, "residents_in_place": 0},                # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "drain", "replica": 0, "migrated": "all",
         "residents_in_place": 0.5},                             # drift
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "roles": "prefill:1,decode:1",
         "migrations": 8, "migrations_in": 8, "migrations_out": 8,
         "migration_restore_s": 0.2, "per_role": {"prefill": {}},
         "disagg_slo_attainment": 0.97},                         # ok
        {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
         "event": "report", "roles": 2, "migrations": "many",
         "migrations_in": 8.5, "migrations_out": [8],
         "migration_restore_s": "slow", "per_role": "both",
         "disagg_slo_attainment": "mostly"},                     # drift
    ]
    bad4.write_text("\n".join(json.dumps(r) for r in rows4) + "\n")
    proc4 = _run(str(bad4))
    assert proc4.returncode == 1
    assert "optional field 'from_replica'" in proc4.stdout
    assert "optional field 'migration_bytes'" in proc4.stdout
    assert "optional field 'migrated'" in proc4.stdout
    assert "optional field 'residents_in_place'" in proc4.stdout
    assert "optional field 'roles'" in proc4.stdout
    assert "optional field 'migrations'" in proc4.stdout
    assert "optional field 'migrations_in'" in proc4.stdout
    assert "optional field 'migrations_out'" in proc4.stdout
    assert "optional field 'migration_restore_s'" in proc4.stdout
    assert "optional field 'per_role'" in proc4.stdout
    assert "optional field 'disagg_slo_attainment'" in proc4.stdout


def test_validator_accepts_anomaly_and_flight_artifacts(tmp_path):
    """Anomaly events and flight dumps are schema-valid artifacts the
    validator blesses like any event stream."""
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        obs.scalar("train/loss", 1.0, 1)
        det = obs.anomalies()
        for i in range(10):
            det.observe_step_time(i, 0.1)
        det.observe_step_time(10, 9.0)
        obs.flush()
        flights = [f for f in os.listdir(out)
                   if f.startswith("flight_")]
        assert flights
    finally:
        obs.reset()
    proc = _run(str(out / "events.jsonl"),
                *(str(out / f) for f in flights))
    assert proc.returncode == 0, proc.stdout


def test_validator_rejects_bad_trace(tmp_path):
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "a", "ph": "X", "ts": 1.0, "pid": 0, "tid": 1},  # no dur
    ]}))
    proc = _run(str(trace))
    assert proc.returncode == 1
    assert "without numeric 'dur'" in proc.stdout
