"""Rematerialization policies (models/layers.py::remat_policy).

``remat`` trades recompute FLOPs for HBM; ``remat_policy`` controls
WHAT is recomputed ("full" = save nothing; "dots" saves matmul outputs
and recomputes only elementwise ops; "dots_no_batch" also drops
batch-dim matmul results). All of them are numerics-preserving by
construction — these tests pin that: loss AND gradients must be
bit-comparable to the no-remat baseline on every policy and family
entry point.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
    remat_policy,
)

SEQ = 16


def _loss_and_grads(remat, policy="full"):
    cfg = EncoderConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=SEQ, hidden_dropout=0.0,
                        attention_dropout=0.0, remat=remat,
                        remat_policy=policy)
    model = BertForSequenceClassification(cfg, num_labels=2)
    params = init_params(model, cfg, seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (4, SEQ)))
    labels = jnp.asarray(rng.randint(0, 2, (4,)))

    def loss(p):
        logits = model.apply({"params": p}, ids, deterministic=True)
        import optax
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, labels))

    val, grads = jax.jit(jax.value_and_grad(loss))(params)
    return float(val), jax.device_get(grads)


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch"])
def test_remat_policies_match_no_remat(policy):
    base_val, base_grads = _loss_and_grads(remat=False)
    val, grads = _loss_and_grads(remat=True, policy=policy)
    assert val == pytest.approx(base_val, rel=1e-6)
    for a, b in zip(jax.tree.leaves(base_grads), jax.tree.leaves(grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_unknown_policy_raises():
    with pytest.raises(ValueError, match="remat_policy"):
        remat_policy("bogus")
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
        TrainConfig,
    )
    with pytest.raises(ValueError, match="remat_policy"):
        TrainConfig(remat_policy="bogus")


def test_gpt2_remat_policy_runs():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64,
                     max_position_embeddings=SEQ, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     remat=True, remat_policy="dots")
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg, seed=0)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, SEQ)))

    def loss(p):
        return jnp.sum(model.apply({"params": p}, ids,
                                   deterministic=True) ** 2)

    val = jax.jit(jax.value_and_grad(loss))(params)[0]
    assert np.isfinite(float(val))


@pytest.mark.slow
def test_remat_policy_override_reaches_every_family(tmp_path):
    """scripts/train.py passes remat_policy into every family's config
    builder — each from_hf constructor must accept it (DeBERTa was the
    one with its own config class that initially did not)."""
    import transformers

    from huggingface_sagemaker_tensorflow_distributed_tpu.models import (
        auto as auto_models,
    )

    cases = [
        ("transformers.BertConfig", dict(vocab_size=128, hidden_size=32,
                                         num_hidden_layers=1,
                                         num_attention_heads=2,
                                         intermediate_size=64)),
        ("transformers.DebertaV2Config", dict(vocab_size=128, hidden_size=32,
                                              num_hidden_layers=1,
                                              num_attention_heads=2,
                                              intermediate_size=64)),
        ("transformers.GPT2Config", dict(vocab_size=128, n_embd=32,
                                         n_layer=1, n_head=2, n_inner=64)),
        ("transformers.T5Config", dict(vocab_size=128, d_model=32, d_kv=16,
                                       d_ff=64, num_layers=1, num_heads=2)),
        ("transformers.BartConfig", dict(vocab_size=128, d_model=32,
                                         encoder_layers=1, decoder_layers=1,
                                         encoder_attention_heads=2,
                                         decoder_attention_heads=2,
                                         encoder_ffn_dim=64,
                                         decoder_ffn_dim=64)),
    ]
    tasks = {"GPT2Config": "causal-lm", "T5Config": "seq2seq",
             "BartConfig": "seq2seq"}
    for name, kw in cases:
        cls = getattr(transformers, name.split(".")[1])
        d = str(tmp_path / name.split(".")[1])
        cls(**kw).save_pretrained(d)
        task = tasks.get(name.split(".")[1], "seq-cls")
        _, _, _, cfg = auto_models.from_pretrained(
            d, task=task, from_scratch=True,
            remat=True, remat_policy="dots")
        assert cfg.remat_policy == "dots", name
