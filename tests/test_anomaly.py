"""Anomaly plane tests (ISSUE 4): synthetic-fault injection — a NaN
loss and a forced step-time spike each produce EXACTLY ONE rate-limited
``anomaly`` event, a flight-recorder dump, and (when enabled) a
profiler trace directory; healthy runs produce ZERO anomaly events.
Plus the straggler-alert satellite, the flight ring's bound, the
FLOPs/peak table, and the bench NaN-exit contract.
"""

import json
import math
import os

import pytest
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.anomaly import (
    AnomalyDetector,
    STEP_MIN_HISTORY,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.flight import (
    FlightRecorder,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.obs import flops


@pytest.fixture()
def obs_dir(tmp_path):
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    yield out
    obs.reset()


def _events(out):
    path = out / "events.jsonl"
    if not path.exists():
        return []
    return [e for _, e, err in obs.iter_events(str(path)) if err is None]


def _anomalies(out):
    return [e for e in _events(out) if e["type"] == "anomaly"]


# -- synthetic faults (acceptance gate) --------------------------------------

def test_nan_loss_fires_exactly_once_with_flight_dump(obs_dir):
    det = obs.anomalies()
    for i in range(16):
        det.observe_loss(i, 0.5)          # healthy prefix fills the ring
    for i in range(16, 24):
        det.observe_loss(i, float("nan"))  # NaN persists: must NOT re-fire
    anoms = _anomalies(obs_dir)
    assert len(anoms) == 1
    ev = anoms[0]
    assert ev["name"] == "nan_loss" and ev["step"] == 16
    assert obs.validate_event(ev) == []
    # the flight dump exists, is schema-valid, and ends with the anomaly
    assert ev.get("evidence") and os.path.exists(ev["evidence"])
    count, errors = obs.validate_events_file(ev["evidence"])
    assert errors == [] and count > 0
    rows = [json.loads(ln) for ln in open(ev["evidence"])]
    assert rows[-1]["type"] == "anomaly"


def test_step_time_spike_fires_once_per_episode(obs_dir):
    det = obs.anomalies()
    for i in range(STEP_MIN_HISTORY + 4):
        det.observe_step_time(i, 0.1)
    assert det.total == 0                  # steady state: no anomalies
    det.observe_step_time(100, 3.0)        # forced spike
    anoms = _anomalies(obs_dir)
    assert [a["name"] for a in anoms] == ["step_time_spike"]
    assert anoms[0]["step"] == 100
    # cooldown: an immediate second spike does not double-report
    det.observe_step_time(101, 3.0)
    assert len(_anomalies(obs_dir)) == 1


def test_profiler_window_on_anomaly(obs_dir, monkeypatch):
    monkeypatch.setenv("HSTD_PROFILE_ON_ANOMALY", "force")
    monkeypatch.setenv("HSTD_PROFILE_SECS", "0.0")  # close on next observe
    det = AnomalyDetector(obs.state(), recorder=obs.state().ring)
    det.observe_loss(0, float("inf"))
    ev = _anomalies(obs_dir)[0]
    assert ev.get("profile_dir")
    det.observe_loss(1, 0.5)    # poll() past the window: trace closes
    det.shutdown()
    assert os.path.isdir(ev["profile_dir"])   # jax.profiler wrote the dir


def test_grad_explosion_and_nan_grad(obs_dir):
    det = obs.anomalies()
    for i in range(12):
        det.observe_grad_norm(i, 1.0)
    assert det.total == 0
    det.observe_grad_norm(20, 50.0)        # 50x the rolling median
    assert det.counts.get("grad_explosion") == 1
    det.observe_grad_norm(21, float("nan"))
    assert det.counts.get("nan_grad") == 1


def test_straggler_alert_names_slow_host(obs_dir):
    det = obs.anomalies()
    stats = {"straggler_ratio": 1.3, "argmax": 2, "n_hosts": 4}
    assert not det.observe_straggler(0, stats)       # 1st epoch: armed
    assert not det.observe_straggler(1, {**stats, "straggler_ratio": 1.05})
    assert not det.observe_straggler(2, stats)       # run was reset
    assert det.observe_straggler(3, stats)           # 2 consecutive
    ev = _anomalies(obs_dir)[0]
    assert ev["name"] == "straggler" and ev["slow_host"] == 2
    assert "host 2" in ev["message"]


def test_begin_fit_resets_rolling_baselines(obs_dir):
    det = obs.anomalies()
    for i in range(12):
        det.observe_step_time(i, 0.01)
    det.begin_fit()
    # a second fit's much slower (but steady) regime is NOT a spike —
    # the rolling baseline was reset with the new run
    for i in range(12):
        det.observe_step_time(i, 0.5)
    assert det.total == 0


def test_disabled_detector_is_inert(obs_dir, monkeypatch):
    monkeypatch.setenv("HSTD_ANOMALY", "0")
    det = AnomalyDetector(obs.state(), recorder=obs.state().ring)
    det.observe_loss(0, float("nan"))
    det.observe_step_time(0, 99.0)
    assert det.total == 0 and _anomalies(obs_dir) == []


# -- flight ring -------------------------------------------------------------

def test_flight_ring_is_bounded_and_dump_ordered(tmp_path):
    ring = FlightRecorder(capacity=8)
    for i in range(50):
        ring.record({"v": 1, "t": float(i), "host": 0, "pid": 1,
                     "type": "metric", "name": "x", "value": float(i)})
    assert len(ring) == 8
    path = ring.dump(str(tmp_path), 50)
    rows = [json.loads(ln) for ln in open(path)]
    assert [r["value"] for r in rows] == [float(i) for i in range(42, 50)]
    # a second dump for the same step keeps the first (no clobbering)
    ring.record({"v": 1, "t": 99.0, "host": 0, "pid": 1,
                 "type": "metric", "name": "y", "value": 99.0})
    assert ring.dump(str(tmp_path), 50) == path
    assert len([json.loads(ln) for ln in open(path)]) == 8


# -- FLOPs / peak table ------------------------------------------------------

def test_peak_tflops_table_and_override(monkeypatch):
    assert flops.peak_tflops("TPU v5 lite") == 197.0
    assert flops.peak_tflops("TPU v4") == 275.0
    assert flops.peak_tflops("Intel Xeon") is None
    monkeypatch.setenv(flops.ENV_PEAK, "2.5")
    assert flops.peak_tflops("Intel Xeon") == 2.5    # override wins
    assert flops.peak_tflops("TPU v4") == 2.5
    monkeypatch.setenv(flops.ENV_PEAK, "bogus")
    assert flops.peak_tflops("Intel Xeon") is None


def test_train_flops_per_token_families():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
    )

    gpt2 = Gpt2Config()      # 124M: 12L/768H/3072FFN/50257V
    f = flops.train_flops_per_token(gpt2, "causal-lm", 512)
    # 3x(12*(8*768^2 + 4*512*768 + 4*768*3072) + 2*768*50257)
    assert f == pytest.approx(3 * (12 * (8 * 768**2 + 4 * 512 * 768
                                         + 4 * 768 * 3072)
                                   + 2 * 768 * 50257))
    # llama family: gated MLP (3 matmuls) + GQA-scaled kv projections
    llama = LlamaConfig(vocab_size=1000, hidden_size=64, num_layers=2,
                        num_heads=4, num_kv_heads=2, intermediate_size=128)
    f = flops.train_flops_per_token(llama, "causal-lm", 64)
    assert f == pytest.approx(3 * (2 * (2 * 64 * 64 * 3 + 4 * 64 * 64
                                        + 6 * 64 * 128) + 2 * 64 * 1000))
    # mlm pays the head only on the masked fraction
    enc = Gpt2Config()
    full = flops.train_flops_per_token(enc, "causal-lm", 512)
    mlm = flops.train_flops_per_token(enc, "mlm", 512)
    assert mlm < full
    # sparse MoE: routed surcharge applies to layers//moe_every layers
    # only — the mixtral bench convention (top_k-1 extra MLPs each)
    moe = LlamaConfig(vocab_size=1000, hidden_size=64, num_layers=4,
                      num_heads=4, num_kv_heads=2, intermediate_size=128,
                      num_experts=8, expert_top_k=2, moe_every=2)
    dense_f = flops.train_flops_per_token(
        LlamaConfig(vocab_size=1000, hidden_size=64, num_layers=4,
                    num_heads=4, num_kv_heads=2, intermediate_size=128),
        "causal-lm", 64)
    moe_f = flops.train_flops_per_token(moe, "causal-lm", 64)
    assert moe_f == pytest.approx(dense_f + 3 * 2 * 1 * 6 * 64 * 128)
    assert flops.mfu(10.0, 100.0) == pytest.approx(0.1)
    assert flops.mfu(None, 100.0) is None and flops.mfu(10.0, None) is None


def test_trainer_flops_speaks_t5_and_bart_dialects():
    """Regression: seq2seq configs use d_model/d_ff (T5) and
    d_model/encoder_ffn_dim (BART) — the accounting must produce
    positive figures for both, and NEVER raise (a config the model
    doesn't understand degrades to (0, 0), not a crashed fit)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
    )

    for cfg in (T5Config(), BartConfig()):
        enc, dec = flops.trainer_flops_per_token(cfg, "seq2seq", 128)
        assert enc > 0 and dec > enc    # decoder adds cross-attn + head
    # T5 v1.1 gated MLP costs more than the same dims ungated
    plain = flops.trainer_flops_per_token(T5Config(), "seq2seq", 128)
    gated = flops.trainer_flops_per_token(
        T5Config(feed_forward_proj="gated-gelu"), "seq2seq", 128)
    assert gated[0] > plain[0]
    # junk config: degrade, don't raise

    class Junk:
        pass

    assert flops.trainer_flops_per_token(Junk(), "seq2seq", 128) == (0.0,
                                                                     0.0)
    assert flops.trainer_flops_per_token(None, "causal-lm", 128) == (0.0,
                                                                     0.0)


def test_flight_dump_schema_valid_without_event_log(tmp_path, monkeypatch):
    """Regression: a host that owns no event log (rank != 0) must still
    write an envelope-stamped, schema-valid flight dump."""
    obs.reset(out_dir=str(tmp_path / "t"), enabled=True)
    try:
        obs.set_host(1, 2)            # demoted: events.jsonl closed
        assert not obs.has_sink()
        det = obs.anomalies()
        det.observe_loss(5, float("nan"))
        flights = [f for f in os.listdir(tmp_path / "t")
                   if f.startswith("flight_")]
        assert flights
        count, errors = obs.validate_events_file(
            str(tmp_path / "t" / flights[0]))
        assert errors == [] and count == 1
        rows = [json.loads(ln)
                for ln in open(tmp_path / "t" / flights[0])]
        assert rows[-1]["host"] == 1 and rows[-1]["type"] == "anomaly"
    finally:
        obs.reset()


# -- end-to-end: trainer fault injection -------------------------------------

def _fit(tmp_path, lr, n=48, log_every=1):
    from tests.test_trainer import _data, _tiny_model
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
        TrainConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ShardedBatcher,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    cfg = TrainConfig(epochs=1, train_batch_size=2, dtype="float32",
                      learning_rate=lr, scale_lr_by_world_size=False,
                      output_data_dir=str(tmp_path),
                      log_every_steps=log_every)
    mesh = build_mesh(MeshConfig())
    model, params = _tiny_model()
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(_data(n=n), 16, mesh, shuffle=False, seed=0)
    return trainer.fit(batcher)


def test_healthy_fit_emits_zero_anomalies_and_mfu(obs_dir, tmp_path,
                                                  monkeypatch):
    monkeypatch.setenv(flops.ENV_PEAK, "0.5")
    hist = _fit(tmp_path, lr=1e-3)
    assert _anomalies(obs_dir) == []
    assert not [f for f in os.listdir(obs_dir)
                if f.startswith("flight_")]
    # MFU accounting flowed through: history figure + metric series
    assert 0 < hist["train_mfu"] <= 1.0
    names = {e.get("name") for e in _events(obs_dir)
             if e["type"] == "metric"}
    assert {"train/mfu", "train/step_time_s", "train/model_flops",
            "train/achieved_tflops_per_chip"} <= names


def test_nan_loss_fit_triggers_anomaly_and_flight_dump(obs_dir, tmp_path):
    # lr large enough to overflow float32 params in one update: the
    # next step's loss is non-finite — the divergence CI must catch
    hist = _fit(tmp_path, lr=1e32)
    assert any(not math.isfinite(loss) for loss in hist["loss"])
    anoms = _anomalies(obs_dir)
    kinds = {a["name"] for a in anoms}
    assert kinds & {"nan_loss", "nan_grad"}
    assert len([a for a in anoms if a["name"] == "nan_loss"]) <= 1
    assert [f for f in os.listdir(obs_dir) if f.startswith("flight_")]
    for a in anoms:
        assert obs.validate_event(a) == []


# -- bench divergence exit ---------------------------------------------------

def test_bench_child_exits_nonzero_on_nan_loss(obs_dir):
    import bench

    det = obs.anomalies()
    bench._check_divergence_exit()          # healthy: no exit
    det.observe_loss(0, float("nan"))
    with pytest.raises(SystemExit) as exc:
        bench._check_divergence_exit()
    assert exc.value.code == bench.ANOMALY_RC


def test_bench_emit_carries_mfu_and_anomalies(obs_dir, monkeypatch, capsys):
    import bench

    monkeypatch.setenv(flops.ENV_PEAK, "100.0")
    bench.emit("m", 10.0, 1.0, flops_per_sample=1e9)
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["mfu"] == pytest.approx(10.0 * 1e9 / 1e12 / 100.0)
    assert 0 < line["mfu"] <= 1.0
    assert line["anomalies"] == 0
    obs.anomalies().observe_loss(0, float("nan"))
    bench.emit("m", 10.0, 1.0)
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["anomalies"] == 1 and line["anomaly_kinds"] == {
        "nan_loss": 1}
