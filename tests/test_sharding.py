"""Mesh/sharding unit tests: axis resolution, param partition rules,
tensor/FSDP sharded training step runs and matches DP numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    param_shardings,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 16


def test_mesh_resolve():
    assert MeshConfig(dp=-1).resolve(8) == (1, 8, 1, 1, 1, 1, 1)
    assert MeshConfig(dp=-1, fsdp=2, tp=2).resolve(8) == (1, 2, 2, 1, 1, 1, 2)
    assert MeshConfig(dp=-1, ep=4).resolve(8) == (1, 2, 1, 4, 1, 1, 1)
    assert MeshConfig(dp=-1, pp=4).resolve(8) == (1, 2, 1, 1, 4, 1, 1)
    assert MeshConfig(dp=-1, dcn_dp=2).resolve(8) == (2, 4, 1, 1, 1, 1, 1)
    assert MeshConfig(dp=-1, dcn_dp=2, tp=2).resolve(8) == (2, 2, 1, 1, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(dp=3, fsdp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(fsdp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dcn_dp=3).resolve(8)


def test_dcn_training_parity(devices8):
    """dp-over-dcn × dp-over-ici ≡ flat dp: the same global batch on a
    dcn2×dp4 mesh and a dp8 mesh must give the same loss sequence (both
    are pure data parallelism; only the collective hierarchy differs).
    Params stay replicated across dcn (checked via the divergence
    instrument, which now spans the dcn axis too)."""
    import jax

    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.collectives import (
        replica_divergence,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
        param_shardings,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)

    def run(mesh_cfg):
        mesh = build_mesh(mesh_cfg, devices=devices8)
        model, params = _tiny()
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0,
                          rng_impl="threefry")
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 16, mesh, shuffle=False)
        losses = []
        for step, batch in enumerate(batcher.global_arrays(0)):
            if step >= 3:
                break
            trainer.state, m = trainer._train_step(trainer.state, batch)
            losses.append(float(jax.device_get(m["loss"])))
        return losses, trainer, mesh

    flat, _, _ = run(MeshConfig(dp=-1))
    hier, trainer, mesh = run(MeshConfig(dp=-1, dcn_dp=2))
    assert mesh.shape["dcn"] == 2 and mesh.shape["data"] == 4
    np.testing.assert_allclose(hier, flat, rtol=1e-5)
    dev = float(replica_divergence(
        trainer.state.params, mesh,
        param_shardings(trainer.state.params, mesh)))
    assert dev == 0.0, f"params diverged across dcn replicas: {dev}"


def _tiny(vocab=256, hidden=64):
    cfg = EncoderConfig(vocab_size=vocab, hidden_size=hidden, num_layers=2,
                        num_heads=4, intermediate_size=128,
                        max_position_embeddings=SEQ)
    model = BertForSequenceClassification(cfg, num_labels=2)
    return model, init_params(model, cfg)


def test_param_partition_rules(devices8):
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2), devices=devices8)
    model, params = _tiny()
    shardings = param_shardings(params, mesh)
    enc = shardings["backbone"]["encoder"]["layer_0"]
    # Megatron layout: qkv column-parallel, attn-out row-parallel
    assert enc["attention"]["query"]["kernel"].spec == P("fsdp", "tensor")
    assert enc["attention"]["attention_out"]["kernel"].spec == P("tensor", "fsdp")
    assert enc["ffn"]["intermediate"]["kernel"].spec == P("fsdp", "tensor")
    assert enc["ffn"]["ffn_out"]["kernel"].spec == P("tensor", "fsdp")
    # LN replicated; embeddings vocab-sharded over fsdp
    assert enc["attention_ln"]["scale"].spec == P()
    emb = shardings["backbone"]["embeddings"]["word_embeddings"]["embedding"]
    assert emb.spec == P("fsdp")


def test_rules_skip_non_divisible_dims(devices8):
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, tp=8), devices=devices8)
    model, params = _tiny(hidden=64)  # 64 % 8 == 0 → sharded
    sh = param_shardings(params, mesh)
    assert sh["backbone"]["encoder"]["layer_0"]["attention"]["query"]["kernel"].spec \
        == P(None, "tensor")
    # num_labels=2 classifier out dim can't shard over 8; fsdp=1 → fully replicated
    assert sh["classifier"]["kernel"].spec == P()


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(dp=2, fsdp=2, tp=2),
    MeshConfig(dp=1, fsdp=4, tp=2),
    MeshConfig(dp=4, fsdp=2, tp=1, sp=1),
])
def test_sharded_train_step_matches_single_device(devices8, mesh_cfg):
    """dp/fsdp/tp mesh runs the identical update as a 1-device mesh —
    the generalization of the reference's DP-only allreduce correctness."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(32, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)

    losses = []
    for dev, mc in ((devices8[:1], MeshConfig()), (devices8, mesh_cfg)):
        mesh = build_mesh(mc, devices=dev)
        cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                          scale_lr_by_world_size=False, log_every_steps=0)
        model, params = _tiny()
        trainer = Trainer(cfg, model, params, mesh)
        batcher = ShardedBatcher(ds, 8, mesh, shuffle=False)
        run = []
        for batch in batcher.global_arrays(0):
            trainer.state, m = trainer._train_step(trainer.state, batch)
            run.append(float(jax.device_get(m["loss"])))
        losses.append(run)
    np.testing.assert_allclose(losses[1], losses[0], atol=2e-5)


def test_optimizer_state_sharded_like_params(devices8):
    mesh = build_mesh(MeshConfig(dp=1, fsdp=8), devices=devices8)
    model, params = _tiny(vocab=256)
    cfg = TrainConfig(dtype="float32", log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    # adam mu for an fsdp-sharded embedding must carry the same sharding
    p_shard = trainer.state_shardings.params["backbone"]["embeddings"][
        "word_embeddings"]["embedding"]
    flat = jax.tree_util.tree_leaves_with_path(trainer.state_shardings.opt_state)
    mu_shards = [l for path, l in flat
                 if "word_embeddings" in str(path) and "mu" in str(path)]
    assert mu_shards and mu_shards[0].spec == p_shard.spec
