"""BART family: HF torch parity (post-LN enc-dec, learned offset-2
positions, tied LM head), conversion round-trip, cached generation
parity, trainer integration."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (  # noqa: E402
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (  # noqa: E402
    load_seq2seq,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (  # noqa: E402
    beam_search_generate,
    generate,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer  # noqa: E402

TOL = 3e-4


@pytest.fixture(scope="module")
def bart_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, pad_token_id=1, bos_token_id=0,
        eos_token_id=2, decoder_start_token_id=2, forced_eos_token_id=None)
    d = str(tmp_path_factory.mktemp("bart"))
    m = transformers.BartForConditionalGeneration(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    m.save_pretrained(d)
    return d, m


def _inputs(batch=2, src=10, tgt=6, vocab=128, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(4, vocab, (batch, src))
    mask = np.ones((batch, src), np.int64)
    mask[1, 7:] = 0
    ids[1, 7:] = 1
    dec = r.randint(4, vocab, (batch, tgt))
    dec[:, 0] = 2
    return ids, mask, dec


def test_bart_teacher_forced_parity(bart_dir):
    d, m = bart_dir
    model, params, family, cfg = auto_models.from_pretrained(d, task="seq2seq")
    assert family == "bart"
    ids, mask, dec = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
                  decoder_input_ids=torch.tensor(dec))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        jnp.asarray(dec), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


def test_bart_cached_greedy_matches_hf_generate(bart_dir):
    d, m = bart_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="seq2seq")
    ids, mask, _ = _inputs(batch=2, src=8)
    new = 6
    ours = np.asarray(generate(model, params, ids, mask, max_new_tokens=new))
    with torch.no_grad():
        hf = m.generate(input_ids=torch.tensor(ids),
                        attention_mask=torch.tensor(mask),
                        max_new_tokens=new, num_beams=1, do_sample=False,
                        min_length=0).numpy()
    # HF prepends decoder_start; compare the continuation, padded after
    # EOS on both sides
    for r in range(2):
        h = hf[r][1:]
        o = ours[r][: len(h)]
        stop = min(len(h), new)
        for a, b in zip(o[:stop], h[:stop]):
            assert a == b, (ours, hf)
            if a == cfg.eos_token_id:
                break


def test_bart_export_roundtrip(bart_dir, tmp_path):
    d, m = bart_dir
    model, params, fam, cfg = auto_models.from_pretrained(d, task="seq2seq")
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, fam, cfg)
    m2 = transformers.BartForConditionalGeneration.from_pretrained(out).eval()
    ids, mask, dec = _inputs()
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
              decoder_input_ids=torch.tensor(dec)).logits
        b = m2(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
               decoder_input_ids=torch.tensor(dec)).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


def test_bart_beam_search_runs(bart_dir):
    d, _ = bart_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="seq2seq")
    ids, mask, _ = _inputs(batch=2, src=8)
    out = beam_search_generate(model, params, ids, mask, num_beams=3,
                               max_new_tokens=5)
    assert out.shape == (2, 5)


def test_bart_trains_on_seq2seq(devices8):
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartConfig,
        BartForConditionalGeneration,
    )

    tok = WordHashTokenizer(vocab_size=256)
    sources, targets = load_seq2seq("synthetic", "train", max_samples=48, seed=0)
    ds = ArrayDataset.from_seq2seq(tok, sources, targets,
                                   max_source_length=24, max_target_length=12,
                                   decoder_start_token_id=2, pad_token_id=1,
                                   eos_token_id=2)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    cfg = BartConfig(vocab_size=256, d_model=32, encoder_layers=2,
                     decoder_layers=2, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=32,
                     dropout=0.0)
    model = BartForConditionalGeneration(cfg)
    params = init_params(model, cfg)
    tc = TrainConfig(task="seq2seq", dtype="float32", learning_rate=5e-3,
                     scale_lr_by_world_size=False, log_every_steps=0,
                     rng_impl="threefry", epochs=3)
    trainer = Trainer(tc, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.9


def test_mbart_parity_and_roundtrip(tmp_path):
    """mBART = pre-LN BART + per-stack final LayerNorm; logits parity
    with HF torch and export reload bit-close."""
    torch.manual_seed(5)
    cfg = transformers.MBartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, pad_token_id=1, bos_token_id=0,
        eos_token_id=2, decoder_start_token_id=2, scale_embedding=True,
        forced_eos_token_id=None)
    m = transformers.MBartForConditionalGeneration(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "mbart")
    m.save_pretrained(d)
    model, params, family, our_cfg = auto_models.from_pretrained(d, task="seq2seq")
    assert family == "mbart" and our_cfg.normalize_before
    ids, mask, dec = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
                  decoder_input_ids=torch.tensor(dec))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        jnp.asarray(dec), deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, our_cfg)
    m2 = transformers.MBartForConditionalGeneration.from_pretrained(out).eval()
    with torch.no_grad():
        b = m2(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
               decoder_input_ids=torch.tensor(dec)).logits
    np.testing.assert_allclose(b.numpy(), t_out.logits.numpy(), atol=1e-5)


def test_mbart_cached_greedy_with_forced_bos_matches_hf(tmp_path):
    """mBART cached greedy with forced_bos_token_id: the pre-LN decode
    path + per-step final_ln run under the KV cache, and the forced
    language token matches HF generate token-for-token."""
    torch.manual_seed(6)
    cfg = transformers.MBartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, pad_token_id=1, bos_token_id=0,
        eos_token_id=2, decoder_start_token_id=2, scale_embedding=True,
        forced_bos_token_id=7, forced_eos_token_id=None)
    m = transformers.MBartForConditionalGeneration(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "mbart-gen")
    m.save_pretrained(d)
    model, params, _, our_cfg = auto_models.from_pretrained(d, task="seq2seq")
    assert our_cfg.forced_bos_token_id == 7
    ids, mask, _ = _inputs(batch=2, src=8)
    new = 6
    ours = np.asarray(generate(model, params, ids, mask, max_new_tokens=new))
    assert (ours[:, 0] == 7).all()
    with torch.no_grad():
        hf = m.generate(input_ids=torch.tensor(ids),
                        attention_mask=torch.tensor(mask),
                        max_new_tokens=new, num_beams=1, do_sample=False,
                        min_length=0).numpy()
    for r in range(2):
        h = hf[r][1:]
        for a, b in zip(ours[r][: len(h)], h[: new]):
            assert a == b, (ours, hf)
            if a == our_cfg.eos_token_id:
                break
    # beam path honours the forcing too
    beam = np.asarray(beam_search_generate(model, params, ids, mask,
                                           num_beams=3, max_new_tokens=new))
    assert (beam[:, 0] == 7).all()
