"""Chunked prefill (models/generate.py::generate_causal prefill_chunk).

Long-prompt serving knob: the prefill runs as a lax.scan over fixed
-size chunks writing the same cache slots the single pass would, so
attention memory during prefill is O(chunk x total) per layer instead
of O(P x total). Contract: token-identical output for every padding
layout and for prompts that don't divide the chunk size.
"""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate_causal,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
    Gpt2Config,
    Gpt2LMHeadModel,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
)


def _llama():
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    return model, init_params(model, cfg, seed=0)


def _gpt2():
    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0)
    model = Gpt2LMHeadModel(cfg)
    return model, init_params(model, cfg, seed=0)


@pytest.mark.parametrize("family", ["llama", "gpt2"])
@pytest.mark.parametrize("chunk", [4, 8, 10])
def test_chunked_prefill_matches_single_pass(family, chunk):
    """chunk=10 doesn't divide the 12-token prompt — the wrapper pads to
    a multiple and the padded slots stay masked."""
    model, params = (_llama if family == "llama" else _gpt2)()
    rng = np.random.RandomState(0)
    ids = rng.randint(3, 128, (2, 12))
    want = np.asarray(generate_causal(model, params, ids,
                                      max_new_tokens=10))
    got = np.asarray(generate_causal(model, params, ids, max_new_tokens=10,
                                     prefill_chunk=chunk))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("side", ["left", "right"])
def test_chunked_prefill_padded_prompts(side):
    """Left- and right-padded prompts both survive chunking (the
    last-real-token index is the last set mask bit, robust to the
    chunk padding appended after a left-padded prompt)."""
    model, params = _llama()
    rng = np.random.RandomState(1)
    real = rng.randint(3, 128, (2, 7))
    ids = np.zeros((2, 12), np.int64)
    mask = np.zeros((2, 12), np.int64)
    if side == "left":
        ids[:, 5:] = real
        mask[:, 5:] = 1
    else:
        ids[:, :7] = real
        mask[:, :7] = 1
    want = np.asarray(generate_causal(model, params, ids, mask,
                                      max_new_tokens=8))
    got = np.asarray(generate_causal(model, params, ids, mask,
                                     max_new_tokens=8, prefill_chunk=8))
    np.testing.assert_array_equal(got, want)
