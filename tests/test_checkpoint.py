"""Checkpoint/resume tests — the capability the reference commented out
(scripts/train.py:135-137)."""

import jax
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import MeshConfig, build_mesh
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer
from huggingface_sagemaker_tensorflow_distributed_tpu.train.checkpoint import Checkpointer

SEQ = 16


def _setup(tmp_path, seed=0):
    mesh = build_mesh(MeshConfig())
    cfg = TrainConfig(dtype="float32", learning_rate=1e-3, log_every_steps=0,
                      checkpoint_dir=str(tmp_path / "ckpt"))
    mcfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=1,
                         num_heads=2, intermediate_size=64,
                         max_position_embeddings=SEQ)
    model = BertForSequenceClassification(mcfg, num_labels=2)
    trainer = Trainer(cfg, model, init_params(model, mcfg, seed=seed), mesh)
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    return cfg, trainer, batcher


def test_save_restore_roundtrip(tmp_path):
    cfg, trainer, batcher = _setup(tmp_path)
    for batch in batcher.global_arrays(0):
        trainer.state, _ = trainer._train_step(trainer.state, batch)
    ckpt = Checkpointer(cfg.checkpoint_dir)
    ckpt.save(trainer.state, epoch=1)
    # async save: a SEPARATE manager (fresh process in real resume) only
    # sees the checkpoint once the writer finished
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 4

    # fresh trainer (different init) restores exactly
    _, trainer2, _ = _setup(tmp_path, seed=9)
    restored, epoch, step_in_epoch = Checkpointer(cfg.checkpoint_dir).restore(trainer2.state)
    assert epoch == 1 and step_in_epoch == 0
    assert int(jax.device_get(restored.step)) == 4
    a = jax.tree.leaves(jax.device_get(trainer.state.params))
    b = jax.tree.leaves(jax.device_get(restored.params))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    ckpt.close()


def test_resume_continues_training(tmp_path):
    cfg, trainer, batcher = _setup(tmp_path)
    ckpt = Checkpointer(cfg.checkpoint_dir)
    for batch in batcher.global_arrays(0):
        trainer.state, _ = trainer._train_step(trainer.state, batch)
    ckpt.save(trainer.state, epoch=1)

    _, trainer2, batcher2 = _setup(tmp_path, seed=9)
    restored, epoch, _ = ckpt.restore(trainer2.state)
    trainer2.state = restored
    for batch in batcher2.global_arrays(epoch):
        trainer2.state, m = trainer2._train_step(trainer2.state, batch)
    assert int(jax.device_get(trainer2.state.step)) == 8
    assert np.isfinite(float(jax.device_get(m["loss"])))
    ckpt.close()


def test_mid_epoch_resume_skips_consumed_batches(tmp_path):
    """A checkpoint at step-in-epoch k must resume at batch k of the SAME
    epoch permutation — not replay the epoch (double-applied updates)."""
    cfg, trainer, batcher = _setup(tmp_path)
    ckpt = Checkpointer(cfg.checkpoint_dir)
    it = batcher.global_arrays(0)
    for _ in range(2):
        trainer.state, _ = trainer._train_step(trainer.state, next(it))
    ckpt.save(trainer.state, epoch=0, step_in_epoch=2)

    _, trainer2, batcher2 = _setup(tmp_path, seed=9)
    restored, epoch, step_in_epoch = ckpt.restore(trainer2.state)
    assert (epoch, step_in_epoch) == (0, 2)
    trainer2.state = restored
    resumed = list(batcher2.local_batches(epoch, start_step=step_in_epoch))
    full = list(batcher.local_batches(0))
    assert len(resumed) == len(full) - 2
    np.testing.assert_array_equal(resumed[0]["labels"], full[2]["labels"])
    ckpt.close()


def test_no_checkpoint_returns_none(tmp_path):
    cfg, trainer, _ = _setup(tmp_path)
    ckpt = Checkpointer(str(tmp_path / "empty"))
    assert ckpt.restore(trainer.state) is None
    ckpt.close()


def test_async_save_overlaps_and_restores_identically(tmp_path):
    """Async checkpointing (VERDICT r1 weak #5): a save started during the
    step loop must commit the exact state that was passed to ``save`` —
    not a later one — and be visible to restore after the sync point."""
    cfg, trainer, batcher = _setup(tmp_path)
    ckpt = Checkpointer(cfg.checkpoint_dir, async_save=True)
    snap_params = None
    for i, batch in enumerate(batcher.global_arrays(0)):
        trainer.state, _ = trainer._train_step(trainer.state, batch)
        if i == 1:
            snap_params = jax.device_get(trainer.state.params)
            ckpt.save(trainer.state, epoch=0, step_in_epoch=i + 1)
            # keep stepping while the write is in flight
    ckpt.wait_until_finished()
    restored = ckpt.restore(trainer.state)
    assert restored is not None
    state, epoch, step_in_epoch = restored
    assert (epoch, step_in_epoch) == (0, 2)
    jax.tree.map(np.testing.assert_array_equal,
                 jax.device_get(state.params), snap_params)
    ckpt.close()


def test_divergence_check_passes_on_consistent_replicas(tmp_path):
    cfg, trainer, batcher = _setup(tmp_path)
    for batch in batcher.global_arrays(0):
        trainer.state, _ = trainer._train_step(trainer.state, batch)
    assert trainer.check_replica_divergence() == 0.0


def test_divergence_check_catches_perturbed_replica(devices8):
    """A deliberately corrupted parameter replica on ONE device must trip
    the checkpoint-boundary consistency check (SURVEY.md §5.2)."""
    import pytest

    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.collectives import (
        ReplicaDivergenceError,
    )

    mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
    cfg = TrainConfig(dtype="float32", log_every_steps=0)
    mcfg = EncoderConfig(vocab_size=64, hidden_size=16, num_layers=1,
                         num_heads=2, intermediate_size=32,
                         max_position_embeddings=SEQ)
    model = BertForSequenceClassification(mcfg, num_labels=2)
    trainer = Trainer(cfg, model, init_params(model, mcfg, seed=0), mesh)
    assert trainer.check_replica_divergence() == 0.0

    # corrupt one replica of one leaf: same sharding, device 3 disagrees
    def corrupt(leaf):
        sharding = leaf.sharding
        host = jax.device_get(leaf)
        bufs = []
        for i, d in enumerate(sharding.mesh.devices.flatten()):
            val = host + (1e-2 if i == 3 else 0.0)
            bufs.append(jax.device_put(val.astype(host.dtype), d))
        return jax.make_array_from_single_device_arrays(
            leaf.shape, sharding, bufs)

    params = trainer.state.params
    path = ("classifier", "kernel")
    leaf = params
    for p in path:
        leaf = leaf[p]
    corrupted = jax.tree_util.tree_map_with_path(
        lambda kp, x: corrupt(x)
        if tuple(getattr(k, "key", k) for k in kp) == path else x, params)
    trainer.state = trainer.state.replace(params=corrupted)
    with pytest.raises(ReplicaDivergenceError):
        trainer.check_replica_divergence()
