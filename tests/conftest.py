"""Test harness: virtual 8-device CPU mesh (SURVEY.md §4).

Forces JAX onto 8 fake CPU devices so the REAL mesh/pjit/collective code
paths run with no TPU and no cluster — the JAX-native fake backend. Must
run before any backend initialization: the env var seeds XLA, and
``jax.config.update`` overrides the axon/TPU platform this container
pins via ``JAX_PLATFORMS``.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu"
    return devs


@pytest.fixture()
def rng():
    return np.random.RandomState(0)
