"""Test harness: virtual 8-device CPU mesh (SURVEY.md §4).

Forces JAX onto 8 fake CPU devices so the REAL mesh/pjit/collective code
paths run with no TPU and no cluster — the JAX-native fake backend. Must
run before any backend initialization: the env var seeds XLA, and
``jax.config.update`` overrides the axon/TPU platform this container
pins via ``JAX_PLATFORMS``.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# The persistent XLA compilation cache is a TPU warm-start feature; on
# the CPU test mesh it buys nothing and the in-process CLI tests
# (test_streaming/test_tasks call scripts.train.main directly) would
# otherwise enable it for the WHOLE pytest process — where serializing
# the suite's largest executables has segfaulted zstd inside jaxlib.
# Empty string = disabled (config.py contract).
os.environ["TPU_COMPILATION_CACHE_DIR"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu"
    return devs


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Free each module's compiled executables when it finishes. The
    full suite jits thousands of programs in one process; keeping them
    all resident exhausts per-process native resources (mapped JIT code
    regions) and XLA's CPU compiler eventually segfaults mid-compile
    around test 400 — modules are self-contained compilation-wise, so
    dropping caches between them costs little and caps the footprint."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture()
def rng():
    return np.random.RandomState(0)


# --- fast/full tiering (VERDICT r2 next-steps #7) ---------------------------
# The full suite needs ~11-14 min on a 1-core box; time-budgeted gates
# run `pytest -m "not slow"` (<2 min). Every test measured >=4s on the
# 1-core reference run is listed here (plus new integration tests as
# they're added); the full suite stays the default for the builder loop.
_SLOW_TESTS = {
    "test_launcher.py",          # whole module: multi-process e2e jobs
    "test_mesh32.py",            # 32-virtual-device subprocess parity
    "test_bf16_quality.py",      # full bf16-vs-fp32 training runs
    "test_t5.py::test_cached_decode_matches_teacher_forcing",
    "test_trainer.py::test_bf16_training_quality_matches_fp32",
    "test_pipeline_parallel.py::test_pp_mesh_training_matches_single_device",
    "test_pipeline_parallel.py::test_gpt2_pp_mesh_training_matches_single_device",
    "test_pipeline_parallel.py::test_pipelined_grads_match_dense",
    "test_pipeline_parallel.py::test_gpt2_pipelined_grads_match_dense",
    "test_pipeline_parallel.py::test_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_gpt2_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_dropout_runs_under_pipeline",
    "test_pipeline_parallel.py::test_non_dividing_microbatches_degrade_to_gcd",
    "test_pipeline_parallel.py::test_hf_checkpoint_loads_into_pipelined_model",
    "test_pipeline_parallel.py::test_llama_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_llama_qwen2_bias_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_llama_pipelined_grads_match_dense",
    "test_pipeline_parallel.py::test_llama_hf_checkpoint_roundtrips_through_pipelined",
    "test_pipeline_parallel.py::test_llama_pipelined_decode_raises",
    "test_pipeline_parallel.py::test_llama_pp_mesh_training_matches_single_device",
    "test_moe.py::test_ep_with_tp_matches_single_device",
    "test_moe.py::test_ep_sharded_matches_single_device",
    "test_moe.py::test_aux_loss_reaches_training_loss",
    "test_moe.py::test_moe_forward_and_routing_conservation",
    "test_trainer.py::test_alternative_optimizers_learn",
    "test_sharding.py::test_sharded_train_step_matches_single_device",
    "test_sharding.py::test_param_partition_rules",
    "test_bart.py::test_bart_trains_on_seq2seq",
    "test_bart.py::test_bart_teacher_forced_parity",
    "test_bart.py::test_mbart_cached_greedy_with_forced_bos_matches_hf",
    "test_bart.py::test_bart_beam_search_runs",
    "test_tasks.py::test_token_cls_learns",
    "test_tasks.py::test_qa_learns",
    "test_trainer.py::test_dp8_matches_dp1_loss_curve",
    "test_ring_attention.py::test_bert_train_step_with_ring_attention",
    "test_ring_attention.py::test_ring_gradients_match",
    "test_t5_ring.py::test_t5_ring_encoder_matches_xla",
    "test_t5_ring.py::test_t5_ring_generate_matches_xla",
    "test_span_corruption.py::test_t5_trains_on_span_corruption",
    "test_trainer.py::test_gradient_accumulation_matches_big_batch",
    "test_gpt2.py::test_gpt2_incremental_decode_matches_full",
    "test_gpt2.py::test_gpt2_generate_left_padded",
    "test_gpt2.py::test_gpt2_causal_lm_training_learns",
    "test_trainer.py::test_eval_with_padded_tail_is_exact",
    "test_trainer.py::test_training_learns",
    "test_trainer.py::test_bf16_compute_runs",
    "test_trainer.py::test_results_files_contract",
    "test_checkpoint.py::test_resume_continues_training",
    "test_checkpoint.py::test_save_restore_roundtrip",
    "test_checkpoint.py::test_async_save_overlaps_and_restores_identically",
    "test_checkpoint.py::test_mid_epoch_resume_skips_consumed_batches",
    "test_checkpoint.py::test_divergence_check_passes_on_consistent_replicas",
    "test_checkpoint.py::test_divergence_check_catches_perturbed_replica",
    "test_t5.py::test_seq2seq_training_learns",
    "test_t5.py::test_forward_shapes_finite",
    "test_deberta.py::test_deberta_training_learns",
    "test_deberta.py::test_deberta_v3_style_seq_cls_parity",
    "test_mesh_bench.py::test_profile_breakdown_finds_collectives",
    "test_pallas_attention.py::test_flash_causal_matches_xla_fwd_and_bwd",
    "test_pallas_attention.py::test_flash_qkv_grads_match_xla",
    "test_rtd.py::test_rtd_training_learns",
    "test_mlm.py::test_mlm_training_learns",
    "test_predict.py::test_predict_mlm_fills",
    "test_vocab_ce.py::test_fused_causal_lm_training_matches_unfused",
    # r4 integration tests measured ≥4s uncontended
    "test_pipeline_parallel.py::test_t5_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_t5_pipelined_gated_untied_matches_dense_forward",
    "test_pipeline_parallel.py::test_t5_pp_mesh_training_matches_single_device",
    "test_pipeline_parallel.py::test_t5_hf_checkpoint_roundtrips_through_pipelined",
    "test_pipeline_parallel.py::test_bart_pipelined_matches_dense_forward",
    "test_pipeline_parallel.py::test_bart_hf_checkpoint_roundtrips_through_pipelined",
    "test_vocab_ce.py::test_fused_seq2seq_composes_with_pipelined_t5",
    "test_moe.py::test_gpt2_moe_training_learns",
    "test_moe.py::test_gpt2_moe_generation_works",
    "test_moe.py::test_gpt2_moe_aux_loss_flows_through_fused_ce",
    "test_sharding.py::test_dcn_training_parity",
    "test_vocab_ce.py::test_fused_seq2seq_training_matches_unfused",
    "test_vocab_ce.py::test_fused_mlm_training_matches_unfused",
    "test_tasks.py::test_qa_eval_reports_em_f1",
    "test_streaming.py::test_streaming_cli_mlm",
    "test_bart.py::test_bart_export_roundtrip",
    "test_deberta.py::test_deberta_c2p_only_parity",
    "test_moe.py::test_moe_export_reload_roundtrip",
    # ≥2s band (uncontended measurement, r3) — trimmed so the fast gate
    # lands under 2 minutes on one core
    "test_bart.py::test_bart_cached_greedy_matches_hf_generate",
    "test_t5.py::test_t5_parity_vs_hf",
    "test_sharding.py::test_rules_skip_non_divisible_dims",
    "test_bart.py::test_mbart_parity_and_roundtrip",
    "test_moe.py::test_moe_tiny_capacity_drops_gracefully",
    "test_gpt2.py::test_gpt2_generate_right_padded",
    "test_vocab_ce.py::test_fused_gradients_match_unfused",
    "test_vocab_ce.py::test_fused_matches_unfused_loss_and_pred",
    "test_t5.py::test_sampled_generation_respects_top_k",
    "test_deberta.py::test_deberta_v2_style_separate_pos_proj_parity",
    "test_pallas_attention.py::test_flash_mask_gradient_nonzero",
    "test_gpt2.py::test_gpt2_lm_parity",
    "test_t5.py::test_t5_beam_search_matches_hf",
    "test_t5.py::test_beam_search_pads_after_eos",
    "test_t5.py::test_beam1_score_dominates_greedy",
    "test_t5.py::test_t5_greedy_generate_matches_hf",
    "test_deberta.py::test_deberta_conv_layer_parity",
    "test_checkpoint.py::test_no_checkpoint_returns_none",
    "test_sharding.py::test_optimizer_state_sharded_like_params",
    "test_pipeline_parallel.py::test_pipelined_params_sharded_over_pipe",
    "test_pipeline_parallel.py::test_gpt2_pipelined_decode_raises",
    "test_moe.py::test_moe_params_sharded_over_expert_axis",
    "test_predict.py::test_predict_causal_lm",
    "test_predict.py::test_predict_rtd",
    # r5 re-tier (VERDICT r4 weak #6): everything ≥3s on an idle 1-core
    # box moves out of the gate (measured via --durations this round)
    "test_deberta.py::test_deberta_embedding_size_and_token_types_parity",
    "test_pallas_attention.py::test_flash_sliding_window_matches_banded_xla",
    "test_pipeline_parallel.py::test_bart_pipelined_decode_raises",
    "test_remat.py::test_gpt2_remat_policy_runs",
    "test_pipeline_parallel.py::test_t5_pipelined_decode_raises",
    "test_mixtral.py::test_mixtral_lm_parity",
    "test_mixtral.py::test_upcycle_dense_llama_roundtrips_as_mixtral",
    "test_convert.py::test_roundtrip_identity",   # all params
    "test_predict.py::test_predict_with_lora_adapter",
    "test_llama.py::test_windowed_decode_requires_position_ids_with_mask",
    "test_gpt2.py::test_gpt2_parity_with_left_padding",
    "test_ring_attention.py::test_llama_train_step_with_ring_attention",
    "test_speculative.py",       # whole module: two-model while_loop compiles
    "test_kv_cache.py::test_int8_kv_decode_matches_fp",
    "test_kv_cache.py::test_int8_kv_composes_with_speculative",
    "test_prefill_chunk.py",     # whole module: scan-prefill compiles
    # observability plane (ISSUE 4): first jax.profiler trace ≈ 17s
    "test_anomaly.py::test_profiler_window_on_anomaly",
    "test_beam_causal.py",       # whole module: HF beam parity compiles
    "test_sharded_generation.py",  # whole module: tp-mesh decode compiles
    "test_speculative_seq2seq.py",  # whole module: T5 spec-decode compiles
    # ISSUE 9 paged-kernel tier: the interpret-mode parity MATRIX and
    # the deeper combo/capacity runs are slow (the 41s spec+prefix+int8
    # composition included — tier-1 was at 798s/870s with it); the core
    # engine exactness gates (pallas kernel engaged, int8 under forced
    # preemption, sliding-window Llama) stay tier-1 per the PR 3/5/7
    # acceptance-gate precedent
    "test_paged_kernel.py::test_paged_kernel_matrix_matches_xla",
    "test_serve.py::test_kv_pool_bytes_doubles_int8_admission",
    "test_serve.py::test_engine_sliding_window_pallas_int8_llama",
    "test_serve.py::test_engine_int8_composes_with_speculative_and_prefix",
    # ISSUE 10 offset: the speculative x prefix-cache COMPOSITION gate
    # (17s) moves out of tier-1 to pay for the new timeline gates —
    # the CORE prefix-cache acceptance gates (forced COW, preemption
    # of a sharing request) stay tier-1 per the PR 3/5/7/8 precedent
    "test_serve.py::test_prefix_cache_speculative_serve_exact",
    # ISSUE 12 offset: the heaviest new dispatch-ahead composition
    # (sampled-bitwise + speculative rejection storm under a tight
    # pool, 11s — four full engine runs) moves to the slow tier; the
    # core overlap exactness gates (EOS on the in-flight iteration,
    # bucket switches mid-pipeline, forced preemption + mandatory
    # flush) stay tier-1 per the same precedent
    "test_serve.py::test_overlap_sampled_bitwise_and_spec_rejection_storm",
    # ISSUE 13 offset: the TP exactness gates (bucket boundary +
    # forced preemption, ~16s of SPMD compiles) and the bench smoke's
    # deterministic TP capacity line join tier-1, paid for by moving
    # (a) the TP byte-budget unit test — its 2x-admission claim is
    # tier-1-gated by the bench smoke's admission-depth assert — and
    # (b) the 18s sampled-SPECULATIVE seed-determinism composition
    # (the sampled-plain and speculative-greedy determinism gates
    # each stay tier-1; only their composition moves)
    "test_serve.py::test_tp_engine_kv_pool_bytes_budget_doubles_admission",
    "test_serve.py::test_sampled_speculative_serve_seed_deterministic_across_preemption",
    # ISSUE 14 budget: the heaviest router composition (affinity x
    # speculative x prefix-cache across replicas, 7s) is slow-marked
    # per the PR 10/12 precedent, and the sampled-bitwise x placement
    # composition (2.6s) moves with it as the offset for the smoke
    # bench's new router line — the core router gates (token identity
    # per policy, drain-mid-trace identity + conservation, the
    # randomized drain/restart schedule, the replicas=1 byte-identity
    # allowlist) stay tier-1
    "test_router.py::test_router_affinity_speculative_prefix_composition",
    "test_router.py::test_router_sampled_streams_bitwise_identical_across_placement",
    # ISSUE 15: the retained runtime no-jax SUBPROCESS smokes — the
    # primary gate is now graftlint R1's static reachability
    # (test_graftlint.py, tier-1); the poison runs are the slow-tier
    # backstop covering runtime (lazily-imported) paths R1 sanctions
    "test_telemetry_schema.py::test_validator_runs_without_jax",
    "test_obsctl.py::test_cli_subprocess_smoke_without_jax",
}


def pytest_collection_modifyitems(items):
    for item in items:
        fname = item.fspath.basename
        base_id = f"{fname}::{item.originalname or item.name}"
        if fname in _SLOW_TESTS or base_id in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
