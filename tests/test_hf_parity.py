"""Model-numerics parity vs HF torch implementations (SURVEY.md §7
stage 2: tolerance ~1e-4 on CPU fp32). Tiny configs are instantiated
locally — no network. Covers checkpoint conversion fidelity (hard-part 1)
in both directions: HF→ours (from_pretrained) and ours→HF (export)."""

import numpy as np
import pytest
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402

TOL = 2e-4


def _inputs(vocab, batch=3, seq=12, pad_id=0, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(pad_id + 1, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    mask[1, 8:] = 0
    ids[1, 8:] = pad_id
    return ids, mask


def _compare(tiny_torch, model_dir, task, ids, mask, extra_tol=1.0):
    model, params, family, cfg = auto_models.from_pretrained(
        model_dir, task=task, num_labels=2)
    with torch.no_grad():
        t_out = tiny_torch(input_ids=torch.tensor(ids),
                           attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    if task == "qa":
        for t, j in [(t_out.start_logits, j_out[0]), (t_out.end_logits, j_out[1])]:
            np.testing.assert_allclose(np.asarray(j), t.numpy(),
                                       atol=TOL * extra_tol, rtol=1e-3)
    else:
        np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                                   atol=TOL * extra_tol, rtol=1e-3)
    return model, params, family, cfg


@pytest.fixture(scope="module")
def bert_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    d = str(tmp_path_factory.mktemp("bert"))
    m = transformers.BertForSequenceClassification(cfg).eval()
    m.save_pretrained(d)
    return d, m, cfg


def test_bert_seq_cls_parity(bert_dir):
    d, m, _ = bert_dir
    ids, mask = _inputs(128)
    _compare(m, d, "seq-cls", ids, mask)


def test_bert_qa_parity(bert_dir, tmp_path):
    _, _, cfg = bert_dir
    torch.manual_seed(1)
    m = transformers.BertForQuestionAnswering(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=1)
    _compare(m, str(tmp_path), "qa", ids, mask)


def test_bert_token_cls_parity(bert_dir, tmp_path):
    _, _, cfg = bert_dir
    torch.manual_seed(2)
    m = transformers.BertForTokenClassification(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=2)
    _compare(m, str(tmp_path), "token-cls", ids, mask)


def test_roberta_seq_cls_parity(tmp_path):
    torch.manual_seed(3)
    cfg = transformers.RobertaConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=70, type_vocab_size=1, pad_token_id=1,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    m = transformers.RobertaForSequenceClassification(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(100, pad_id=1, seed=3)
    _compare(m, str(tmp_path), "seq-cls", ids, mask)


def test_distilbert_seq_cls_parity(tmp_path):
    torch.manual_seed(4)
    cfg = transformers.DistilBertConfig(
        vocab_size=120, dim=32, n_layers=2, n_heads=2, hidden_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0,
        seq_classif_dropout=0.0)
    m = transformers.DistilBertForSequenceClassification(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(120, seed=4)
    _compare(m, str(tmp_path), "seq-cls", ids, mask)


def test_export_roundtrip_loads_in_hf(bert_dir, tmp_path):
    """save_pretrained parity (reference train.py:182-183): our export
    must be loadable by HF transformers and produce identical logits."""
    d, m, _ = bert_dir
    ids, mask = _inputs(128, seed=5)
    model, params, family, cfg = auto_models.from_pretrained(d, task="seq-cls")
    out_dir = str(tmp_path / "export")
    auto_models.save_pretrained(out_dir, params, family, cfg)
    reloaded = transformers.BertForSequenceClassification.from_pretrained(out_dir).eval()
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
        b = reloaded(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


def test_fresh_head_when_checkpoint_lacks_it(tmp_path):
    """Loading a bare backbone for a new task initializes the head fresh
    (HF from_pretrained behavior at reference train.py:117)."""
    torch.manual_seed(6)
    cfg = transformers.BertConfig(
        vocab_size=64, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=32, max_position_embeddings=32)
    m = transformers.BertModel(cfg).eval()
    d = str(tmp_path)
    m.save_pretrained(d)
    # state dict has no "bert." prefix and no classifier — both handled
    model, params, family, _ = auto_models.from_pretrained(d, task="seq-cls")
    assert "classifier" in params


@pytest.fixture(scope="module")
def electra_dir(tmp_path_factory):
    torch.manual_seed(5)
    # embedding_size != hidden_size exercises the factorized-embedding
    # projection path (models/layers.py embeddings_project)
    cfg = transformers.ElectraConfig(
        vocab_size=128, embedding_size=16, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    d = str(tmp_path_factory.mktemp("electra"))
    m = transformers.ElectraForSequenceClassification(cfg).eval()
    m.save_pretrained(d)
    return d, m, cfg


def test_electra_seq_cls_parity(electra_dir):
    d, m, _ = electra_dir
    ids, mask = _inputs(128, seed=5)
    _compare(m, d, "seq-cls", ids, mask)


def test_electra_qa_parity(electra_dir, tmp_path):
    _, _, cfg = electra_dir
    torch.manual_seed(6)
    m = transformers.ElectraForQuestionAnswering(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=6)
    _compare(m, str(tmp_path), "qa", ids, mask)


def test_electra_token_cls_parity(electra_dir, tmp_path):
    _, _, cfg = electra_dir
    torch.manual_seed(7)
    m = transformers.ElectraForTokenClassification(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=7)
    _compare(m, str(tmp_path), "token-cls", ids, mask)


def test_electra_export_roundtrip_loads_in_hf(electra_dir, tmp_path):
    d, m, _ = electra_dir
    model, params, family, cfg = auto_models.from_pretrained(
        d, task="seq-cls", num_labels=2)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, cfg)
    reloaded = transformers.ElectraForSequenceClassification.from_pretrained(out).eval()
    ids, mask = _inputs(128, seed=8)
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
        b = reloaded(input_ids=torch.tensor(ids),
                     attention_mask=torch.tensor(mask)).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


@pytest.fixture(scope="module")
def albert_dir(tmp_path_factory):
    torch.manual_seed(9)
    # embedding_size != hidden_size + cross-layer sharing: one shared
    # flax EncoderLayer must reproduce HF's layer-group stack
    cfg = transformers.AlbertConfig(
        vocab_size=128, embedding_size=16, hidden_size=32,
        num_hidden_layers=3, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, classifier_dropout_prob=0.0)
    d = str(tmp_path_factory.mktemp("albert"))
    m = transformers.AlbertForSequenceClassification(cfg).eval()
    m.save_pretrained(d)
    return d, m, cfg


def test_albert_seq_cls_parity(albert_dir):
    d, m, _ = albert_dir
    ids, mask = _inputs(128, seed=9)
    _compare(m, d, "seq-cls", ids, mask)


def test_albert_qa_parity(albert_dir, tmp_path):
    _, _, cfg = albert_dir
    torch.manual_seed(10)
    m = transformers.AlbertForQuestionAnswering(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=10)
    _compare(m, str(tmp_path), "qa", ids, mask)


def test_albert_token_cls_parity(albert_dir, tmp_path):
    _, _, cfg = albert_dir
    torch.manual_seed(11)
    m = transformers.AlbertForTokenClassification(cfg).eval()
    m.save_pretrained(str(tmp_path))
    ids, mask = _inputs(128, seed=11)
    _compare(m, str(tmp_path), "token-cls", ids, mask)


def test_albert_export_roundtrip_loads_in_hf(albert_dir, tmp_path):
    d, m, _ = albert_dir
    model, params, family, cfg = auto_models.from_pretrained(
        d, task="seq-cls", num_labels=2)
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, cfg)
    reloaded = transformers.AlbertForSequenceClassification.from_pretrained(out).eval()
    ids, mask = _inputs(128, seed=12)
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)).logits
        b = reloaded(input_ids=torch.tensor(ids),
                     attention_mask=torch.tensor(mask)).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


def test_albert_head_dropout_follows_classifier_dropout_prob():
    # albert-base-v2 shape: hidden_dropout 0 but classifier_dropout 0.1 —
    # the head must regularize where HF does (inference parity can't see
    # this; assert the config mapping directly)
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.albert import (
        albert_config_from_hf,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
        head_dropout_rate,
    )
    cfg = albert_config_from_hf({
        "vocab_size": 128, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 64,
        "max_position_embeddings": 64, "hidden_dropout_prob": 0.0,
        "classifier_dropout_prob": 0.1})
    assert cfg.hidden_dropout == 0.0
    assert head_dropout_rate(cfg) == 0.1


def test_xlm_roberta_alias_parity(tmp_path):
    """XLM-RoBERTa (model_type xlm-roberta) is architecturally RoBERTa —
    the family alias loads it with full numerics parity."""
    torch.manual_seed(11)
    cfg = transformers.XLMRobertaConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, pad_token_id=1)
    m = transformers.XLMRobertaForSequenceClassification(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "xlmr")
    m.save_pretrained(d)
    model, params, family, _ = auto_models.from_pretrained(
        d, task="seq-cls", num_labels=2)
    assert family == "roberta"
    ids, mask = _inputs(128, pad_id=1)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)
