"""T5 + sequence parallelism (VERDICT r1 weak #7): the encoder's
relative-bias attention must run the ring path on an sp mesh and match
the XLA path exactly — forward, loss, and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models import t5 as t5_mod
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
    use_mesh,
)

# seq lengths divisible by sp=4; heads divisible by tp is not exercised
# here (tp=1) — the 4-axis composition is covered by tests/_mesh32_child.py
SRC, TGT = 32, 8


def _cfg(impl):
    return t5_mod.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        attention_impl=impl)


def _batch(cfg, batch=4, seed=0):
    r = np.random.RandomState(seed)
    src_ids = r.randint(2, cfg.vocab_size, (batch, SRC)).astype(np.int32)
    src_mask = np.ones((batch, SRC), np.int32)
    src_mask[1, 20:] = 0
    src_ids[1, 20:] = cfg.pad_token_id
    tgt_ids = r.randint(2, cfg.vocab_size, (batch, TGT)).astype(np.int32)
    return jnp.asarray(src_ids), jnp.asarray(src_mask), jnp.asarray(tgt_ids)


def _loss_and_grads(impl, mesh):
    cfg = _cfg(impl)
    model = t5_mod.T5ForConditionalGeneration(cfg)
    params = auto_models.init_params(model, cfg, seed=0)
    src_ids, src_mask, tgt_ids = _batch(cfg)

    def loss_fn(p):
        logits = model.apply({"params": p}, src_ids, src_mask, tgt_ids,
                             deterministic=True)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jax.nn.one_hot(tgt_ids, cfg.vocab_size)
        return -jnp.mean(jnp.sum(logp * tgt, axis=-1))

    with use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
        return (float(jax.device_get(loss)),
                jax.device_get(jax.tree.map(np.asarray, grads)))


def test_t5_ring_encoder_matches_xla(devices8):
    mesh = build_mesh(MeshConfig(dp=2, sp=4), devices=devices8)
    loss_x, grads_x = _loss_and_grads("xla", mesh)
    loss_r, grads_r = _loss_and_grads("ring", mesh)
    assert np.isfinite(loss_r)
    np.testing.assert_allclose(loss_r, loss_x, atol=1e-5)
    flat_x = jax.tree.leaves(grads_x)
    flat_r = jax.tree.leaves(grads_r)
    assert len(flat_x) == len(flat_r)
    for gx, gr in zip(flat_x, flat_r):
        np.testing.assert_allclose(gr, gx, atol=2e-5)


def test_t5_ring_param_tree_matches_xla():
    # the ring-mode bias table must create the SAME parameter path/shape
    # (self_attn/rel_bias/embedding) so checkpoints swap between modes
    t_x = auto_models.init_params(
        t5_mod.T5ForConditionalGeneration(_cfg("xla")), _cfg("xla"), seed=0)
    t_r = auto_models.init_params(
        t5_mod.T5ForConditionalGeneration(_cfg("ring")), _cfg("ring"), seed=0)
    paths_x = {jax.tree_util.keystr(p): v.shape
               for p, v in jax.tree_util.tree_flatten_with_path(t_x)[0]}
    paths_r = {jax.tree_util.keystr(p): v.shape
               for p, v in jax.tree_util.tree_flatten_with_path(t_r)[0]}
    assert paths_x == paths_r


def test_t5_ring_generate_matches_xla(devices8):
    # decode path (KV cache) materializes bias from the table — greedy
    # generation must be identical between modes
    from huggingface_sagemaker_tensorflow_distributed_tpu.models import generate as gen

    outs = {}
    mesh = build_mesh(MeshConfig(dp=2, sp=4), devices=devices8)
    for impl in ("xla", "ring"):
        cfg = _cfg(impl)
        model = t5_mod.T5ForConditionalGeneration(cfg)
        params = auto_models.init_params(model, cfg, seed=0)
        src_ids, src_mask, _ = _batch(cfg)
        with use_mesh(mesh):
            outs[impl] = np.asarray(gen.generate(
                model, params, src_ids, src_mask, max_new_tokens=6))
    np.testing.assert_array_equal(outs["ring"], outs["xla"])
