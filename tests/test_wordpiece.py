"""WordPiece tokenizer + native data-loader tests.

Three-way parity: the C++ core (native/wordpiece.cc) against the
pure-Python twin (data/wordpiece.py), and both against HF's
``BertTokenizer`` — the actual implementation the reference uses via
``AutoTokenizer.from_pretrained`` (reference ``scripts/train.py:69``) —
built from a local vocab file (offline).
"""

import os

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.data.native import (
    CppWordPieceTokenizer,
    _py_permutation,
    native_available,
    native_gather,
    native_permutation,
    native_row_lengths,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.wordpiece import (
    WordPieceTokenizer,
)

VOCAB_TOKENS = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "lazy",
    "dog", "un", "##aff", "##able", "run", "!", ",", ".", "-", "hello",
    "world", "re", "##sum", "##e", "2023", "#", "is", "a", "b", "##c",
    "ab", "##b", "new", "york", "city", "in", "of", "what", "?", "and",
    "to", "it", "was", "big", "small", "##ly", "##ing", "work", "play",
]

TEXTS = [
    "The quick brown fox jumped over the lazy dog!",
    "Hello, WORLD! unaffable résumé abc",
    "runs runs RUNS",
    "#2023 is a, b !!",
    "ab abc bc",
    "",
    "   \t\n  ",
    "newly working PLAYING bigly",
    "New York City -- in 2023?",
    "a" * 150 + " ok",            # > max_input_chars_per_word → UNK
    "naïve café über señor",
    "über-big small.and.quick",
    "日本語 text 中文",            # CJK chars split standalone
    "what is this",      # unicode spaces
    "zero​width and bell\x07char",  # control chars dropped
    "co­operate soft­hyphen",   # Cf chars (soft hyphen) dropped
    "a⁠b c‎d ⁦e⁩",    # word joiner, LRM, isolates: all Cf
    # beyond the C++ boundary: routed to the Python twin inside the native
    # tokenizer, so parity must still hold exactly
    # non-decomposing Latin-Ext-A (stroke/bar/eng/dotless): NFD keeps
    # these, so fold_accent must NOT map them to base letters — parity
    # between C++ (below the 0x0180 routing boundary) and Python/HF
    "Łódź złoty ŁÓDŹ",          # Polish l-stroke
    "Đorđe đak Ħal ħobża",      # d-stroke, h-bar
    "kapalı ılık TOPKAPı",      # Turkish dotless i
    "İSTANBUL İzmir diyarbakır",  # dotted capital İ lowers to plain i
    "ŋoro ŧavle ĸra ŉgawe",     # eng, t-stroke, kra, apostrophe-n
    "Ŀlull l·l paral·lel",      # l-middle-dot
    "ёлка and ЁЛКА",            # Cyrillic with NFD-decomposable ё
    "άλφα ΆΛΦΑ βήτα",           # accented Greek
    "што؟ arabic ، question",   # Arabic punctuation
    "mixed ascii then ελληνικά",
]


@pytest.fixture(scope="module")
def vocab():
    return {t: i for i, t in enumerate(VOCAB_TOKENS)}


@pytest.fixture(scope="module")
def py_tok(vocab):
    return WordPieceTokenizer(vocab)


@pytest.fixture(scope="module")
def cc_tok(vocab):
    if not native_available():
        pytest.skip("no C++ toolchain")
    return CppWordPieceTokenizer(vocab)


@pytest.fixture(scope="module")
def hf_tok(vocab, tmp_path_factory):
    transformers = pytest.importorskip("transformers")
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    path.write_text("\n".join(VOCAB_TOKENS) + "\n", encoding="utf-8")
    return transformers.BertTokenizer(str(path), do_lower_case=True)


def test_cpp_python_parity(py_tok, cc_tok):
    for max_length in (8, 32, 128):
        a = py_tok(TEXTS, max_length=max_length)
        b = cc_tok(TEXTS, max_length=max_length)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg=f"{k} @ {max_length}")


def test_cpp_python_parity_token_streams(py_tok, cc_tok):
    """Token-for-token: ids, word alignment, AND char offsets agree."""
    a = py_tok._tokenize_batch(TEXTS, 64)
    b = cc_tok._tokenize_batch(TEXTS, 64)
    for name, x, y in zip(("ids", "word_ids", "starts", "ends", "counts"), a, b):
        np.testing.assert_array_equal(x, y, err_msg=name)


def test_hf_parity(py_tok, hf_tok):
    for text in TEXTS:
        ours = [int(i) for i in py_tok([text], max_length=64)["input_ids"][0] if i != 0]
        theirs = hf_tok(text, max_length=64, truncation=True)["input_ids"]
        assert ours == list(theirs), text


def test_hf_parity_pairs(py_tok, hf_tok):
    qs = ["what is big?", "the fox runs"]
    cs = ["a big dog runs over the lazy fox", "hello world and new york"]
    ours = py_tok(qs, text_pairs=cs, max_length=32)
    theirs = hf_tok(qs, cs, max_length=32, truncation=True,
                    padding="max_length", return_tensors="np")
    np.testing.assert_array_equal(ours["input_ids"], theirs["input_ids"])
    np.testing.assert_array_equal(ours["token_type_ids"], theirs["token_type_ids"])
    np.testing.assert_array_equal(ours["attention_mask"], theirs["attention_mask"])


def test_hf_parity_pairs_truncated(py_tok, hf_tok):
    """longest_first truncation: final [SEP] kept, longer side trimmed."""
    qs = ["what is big and small and quick and lazy?",
          "a b", "the quick brown fox jumped over the lazy dog and ran"]
    cs = ["a big dog runs over the lazy fox in new york city and plays",
          "hello world and new york city in 2023 and the fox", "it was big"]
    for max_length in (8, 12, 16):
        ours = py_tok(qs, text_pairs=cs, max_length=max_length)
        theirs = hf_tok(qs, cs, max_length=max_length, truncation=True,
                        padding="max_length", return_tensors="np")
        np.testing.assert_array_equal(ours["input_ids"], theirs["input_ids"])
        np.testing.assert_array_equal(ours["token_type_ids"],
                                      theirs["token_type_ids"])


def test_encode_words_alignment(cc_tok):
    words = [["newly", "working", "dog"], ["unaffable", "fox"]]
    out = cc_tok.encode_words(words, max_length=16)
    # row 0: CLS new ##ly work ##ing dog SEP → word ids -1 0 0 1 1 2 -1
    assert out["word_ids"][0, :7].tolist() == [-1, 0, 0, 1, 1, 2, -1]
    # row 1: unaffable = un ##aff ##able (word 0), fox (word 1)
    assert out["word_ids"][1, :6].tolist() == [-1, 0, 0, 0, 1, -1]
    assert out["input_ids"][1, 4] == cc_tok.vocab["fox"]


def test_encode_qa_span(cc_tok):
    q = ["what is the dog?"]
    c = ["the quick brown fox jumped over the lazy dog in New York City"]
    ans = "lazy dog"
    start = c[0].index(ans)
    out = cc_tok.encode_qa(q, c, [start], [ans], max_length=64)
    s, e = int(out["start_positions"][0]), int(out["end_positions"][0])
    assert 0 < s <= e
    ids = out["input_ids"][0]
    assert ids[s] == cc_tok.vocab["lazy"]
    assert ids[e] == cc_tok.vocab["dog"]
    assert out["token_type_ids"][0, s] == 1


def test_encode_qa_truncated_answer_is_cls(cc_tok):
    c = ["the quick brown fox " * 40 + "hidden answer dog"]
    start = c[0].index("dog")
    out = cc_tok.encode_qa(["what?"], c, [start], ["dog"], max_length=32)
    assert int(out["start_positions"][0]) == 0
    assert int(out["end_positions"][0]) == 0


def test_encode_qa_long_question(cc_tok):
    """Question longer than max_length-3: question truncated, no crash."""
    q = ["what is the quick brown fox and the lazy dog " * 4]
    out = cc_tok.encode_qa(q, ["the dog"], [4], ["dog"], max_length=16)
    assert out["input_ids"].shape == (1, 16)
    assert int(out["attention_mask"][0].sum()) == 16
    assert int(out["start_positions"][0]) == 0  # answer truncated away


def test_model_max_length_roundtrip(tmp_path, py_tok):
    py_tok.model_max_length = 128
    py_tok.save_pretrained(str(tmp_path))
    again = WordPieceTokenizer.from_pretrained(str(tmp_path))
    assert again.model_max_length == 128
    py_tok.model_max_length = 512  # restore module-scoped fixture


def test_native_gather_bool_mask():
    src = np.arange(12, dtype=np.int32).reshape(4, 3)
    mask = np.array([True, False, True, False])
    np.testing.assert_array_equal(native_gather(src, mask), src[mask])


def test_cpp_rejects_noncontiguous_vocab():
    if not native_available():
        pytest.skip("no C++ toolchain")
    bad = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3, "a": 5}  # gap at 4
    with pytest.raises(RuntimeError):
        CppWordPieceTokenizer(bad)


def test_load_tokenizer_non_bert_specials_falls_back(tmp_path):
    (tmp_path / "vocab.txt").write_text("<pad>\n<unk>\n<s>\n</s>\nhello\n")
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        load_tokenizer,
    )
    tok = load_tokenizer(str(tmp_path))  # must not raise
    assert tok is not None


def test_native_gather_bounds():
    src = np.arange(20, dtype=np.int32).reshape(10, 2)
    with pytest.raises(IndexError):
        native_gather(src, np.array([0, 10]))
    # negative indices keep numpy fancy-indexing semantics
    np.testing.assert_array_equal(native_gather(src, np.array([-1, 0])),
                                  src[np.array([-1, 0])])


def test_save_load_roundtrip(tmp_path, py_tok, cc_tok):
    cc_tok.save_pretrained(str(tmp_path))
    assert (tmp_path / "vocab.txt").exists()
    re_py = WordPieceTokenizer.from_pretrained(str(tmp_path))
    a = py_tok(TEXTS[:4], max_length=32)
    b = re_py(TEXTS[:4], max_length=32)
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])

    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        load_tokenizer,
    )
    auto = load_tokenizer(str(tmp_path))
    assert isinstance(auto, WordPieceTokenizer)  # includes the Cpp subclass
    np.testing.assert_array_equal(
        auto(TEXTS[:4], max_length=32)["input_ids"], a["input_ids"])


def test_threading_determinism(vocab):
    if not native_available():
        pytest.skip("no C++ toolchain")
    texts = [f"the quick brown fox {i} runs over {i*7} lazy dogs!" for i in range(257)]
    one = CppWordPieceTokenizer(vocab, n_threads=1)(texts, max_length=32)
    many = CppWordPieceTokenizer(vocab, n_threads=8)(texts, max_length=32)
    np.testing.assert_array_equal(one["input_ids"], many["input_ids"])


# -- data-loader primitives --------------------------------------------------

def test_native_permutation_deterministic():
    a = native_permutation(10_000, seed=123)
    b = native_permutation(10_000, seed=123)
    np.testing.assert_array_equal(a, b)
    assert sorted(a.tolist()) == list(range(10_000))
    assert not np.array_equal(a, native_permutation(10_000, seed=124))


def test_native_permutation_matches_python_twin():
    if not native_available():
        pytest.skip("no C++ toolchain")
    for n, seed in ((1, 0), (17, 5), (1000, 42)):
        np.testing.assert_array_equal(native_permutation(n, seed),
                                      _py_permutation(n, seed))


def test_native_gather_matches_numpy(rng):
    src = rng.randint(0, 1000, size=(500, 64)).astype(np.int32)
    idx = rng.permutation(500)[:300]
    np.testing.assert_array_equal(native_gather(src, idx), src[idx])
    # 1-D (labels) path
    labels = rng.randint(0, 2, size=500).astype(np.int32)
    np.testing.assert_array_equal(native_gather(labels, idx), labels[idx])


def test_native_row_lengths(rng):
    mask = (rng.rand(100, 32) > 0.5).astype(np.int32)
    np.testing.assert_array_equal(native_row_lengths(mask), mask.sum(axis=1))
