"""Goodput-aware admission control (ISSUE 20): the policy layer's
contracts, host-side and end-to-end.

- knob parsing (`HSTD_SERVE_POLICY` / `HSTD_SERVE_AGING_S`), the
  token-bucket rate limiter, and the `group=rate[:burst]` spec grammar;
- the slo admission key: priority dominates deadline dominates
  predicted demand (prefix-cache-aware), with the aging tier promoted
  ahead of everything and FIFO among itself;
- the property test: a seeded 300-step submit/admit/preempt/finish
  schedule under ``policy=slo`` holding the aging bound (nothing
  younger admits past a starving request), block conservation, and
  no starvation (everything finishes, token counts exact);
- the byte-identity contract: a ``policy="fifo"`` engine's serve-event
  stream is structurally identical to a default-built engine's, with
  ZERO ISSUE-20 fields present — and the schema validator rejects
  mistyped rider rows;
- the router's structured per-tenant rejection: an empty bucket
  returns :class:`RateLimited` (counted, ``retry_after_s`` named),
  never a silent drop.
"""

import types

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (
    validate_event,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    BlockManager,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.policy import (
    DEFAULT_AGING_S,
    ENV_AGING_S,
    ENV_POLICY,
    POLICIES,
    RateLimited,
    SloPolicy,
    TokenBucket,
    parse_aging_s,
    parse_policy,
    parse_rate_limit,
    request_origin,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    DECODE,
    FINISHED,
    PREFILL,
    WAITING,
    Request,
    Scheduler,
)

# -- knob parsing ------------------------------------------------------------


def test_parse_policy_default_env_and_errors(monkeypatch):
    monkeypatch.delenv(ENV_POLICY, raising=False)
    assert parse_policy(None) == "fifo"
    monkeypatch.setenv(ENV_POLICY, "slo")
    assert parse_policy(None) == "slo"
    monkeypatch.setenv(ENV_POLICY, "")
    assert parse_policy(None) == "fifo"
    assert parse_policy(" SLO ") == "slo"
    with pytest.raises(ValueError, match=ENV_POLICY):
        parse_policy("edf")
    assert POLICIES == ("fifo", "slo")


def test_parse_aging_default_env_and_errors(monkeypatch):
    monkeypatch.delenv(ENV_AGING_S, raising=False)
    assert parse_aging_s(None) == DEFAULT_AGING_S
    monkeypatch.setenv(ENV_AGING_S, "2.5")
    assert parse_aging_s(None) == 2.5
    assert parse_aging_s(" 7 ") == 7.0
    for bad in ("soon", "0", "-3", "inf", "nan"):
        with pytest.raises(ValueError, match=ENV_AGING_S):
            parse_aging_s(bad)


def test_scheduler_reads_policy_env(monkeypatch):
    monkeypatch.setenv(ENV_POLICY, "slo")
    monkeypatch.setenv(ENV_AGING_S, "2.5")
    s = Scheduler(1, BlockManager(5, 4), 4, 16)
    assert s.policy == "slo" and s.aging_s == 2.5
    assert isinstance(s._policy, SloPolicy)
    monkeypatch.delenv(ENV_POLICY)
    monkeypatch.delenv(ENV_AGING_S)
    # the default scheduler is the pre-ISSUE-20 one: no policy object
    # at all, so the fifo admit path runs bit-for-bit
    s = Scheduler(1, BlockManager(5, 4), 4, 16)
    assert s.policy == "fifo" and s._policy is None


# -- token bucket + rate-limit grammar ---------------------------------------


def test_token_bucket_refill_burst_and_backwards_clock():
    b = TokenBucket(rate=1.0, burst=2.0)
    assert b.try_take(0.0) == (True, 0.0)
    assert b.try_take(0.0) == (True, 0.0)
    ok, retry = b.try_take(0.0)
    assert not ok and retry == pytest.approx(1.0)
    # lazy refill from the last observed clock; the cap holds
    ok, _ = b.try_take(1.0)
    assert ok
    ok, retry = b.try_take(1.0)
    assert not ok and retry == pytest.approx(1.0)
    # a clock that goes backwards refills nothing and never raises
    ok, retry = b.try_take(0.5)
    assert not ok and retry == pytest.approx(1.0)
    with pytest.raises(ValueError, match="rate"):
        TokenBucket(0.0, 2.0)
    with pytest.raises(ValueError, match="burst"):
        TokenBucket(1.0, 0.5)
    with pytest.raises(ValueError, match="rate"):
        TokenBucket(float("inf"), 2.0)


def test_parse_rate_limit_dict_string_and_errors():
    assert parse_rate_limit(None) == {}
    assert parse_rate_limit("") == {}
    assert parse_rate_limit({"a": (2.0, 4.0)}) == {"a": (2.0, 4.0)}
    # scalar rate: burst defaults to max(1, rate)
    assert parse_rate_limit({"a": 3}) == {"a": (3.0, 3.0)}
    assert parse_rate_limit({"a": 0.5}) == {"a": (0.5, 1.0)}
    assert parse_rate_limit("a=2:4, b=3 ,*=0.5") == {
        "a": (2.0, 4.0), "b": (3.0, 3.0), "*": (0.5, 1.0)}
    with pytest.raises(ValueError, match="group=rate"):
        parse_rate_limit("nope")
    with pytest.raises(ValueError, match="rate"):
        parse_rate_limit("a=0")
    with pytest.raises(ValueError, match="burst"):
        parse_rate_limit("a=2:0")


def test_rate_limited_is_structured_and_frozen():
    r = RateLimited(group="t0", retry_after_s=0.25, rate=2.0, burst=4.0)
    assert r.rejected is True
    assert not getattr(Request(prompt=[1], max_new_tokens=1),
                       "rejected", False)
    with pytest.raises(Exception):
        r.group = "other"


# -- the slo admission key ---------------------------------------------------


def _req(prompt_len=4, max_new=4, **kw):
    return Request(prompt=np.arange(1, prompt_len + 1),
                   max_new_tokens=max_new, **kw)


def test_request_origin_prefers_arrival_over_submit():
    r = _req()
    assert request_origin(r) == 0.0
    r.submit_t = 5.0
    assert request_origin(r) == 5.0
    r.arrival_s = 3.0
    assert request_origin(r) == 3.0


def test_slo_key_priority_deadline_demand_rid_order():
    pol = SloPolicy(aging_s=100.0)
    demand = {"urgent": 2, "soon": 2, "later": 2, "small": 1, "big": 3}
    reqs = {}
    for name, (prio, dl) in {
            "urgent": (0, 1.0), "soon": (1, 1.0), "later": (1, 5.0),
            "small": (1, None), "big": (1, None)}.items():
        r = _req(deadline_s=dl, priority=prio)
        r.arrival_s = 0.0
        reqs[name] = r
    names = {r.rid: n for n, r in reqs.items()}
    ranked = pol.rank(list(reqs.values())[::-1], now=0.0,
                      demand_blocks=lambda r: demand[names[r.rid]])
    order = [names[r.rid] for r in ranked]
    # priority class first, then effective deadline (deadline-less
    # last), then predicted demand, then rid
    assert order == ["urgent", "soon", "later", "small", "big"]
    # same priority/deadline/demand: rid (submission order) breaks ties
    a, b = _req(deadline_s=1.0), _req(deadline_s=1.0)
    a.arrival_s = b.arrival_s = 0.0
    assert pol.rank([b, a], 0.0, lambda r: 1) == [a, b]


def test_aging_tier_promotes_fifo_by_origin_ahead_of_priorities():
    pol = SloPolicy(aging_s=10.0)
    old_lo = _req(priority=5)           # worst class, but starving
    old_lo.arrival_s = 0.0
    older_lo = _req(priority=9)
    older_lo.arrival_s = -1.0
    fresh_hi = _req(priority=0, deadline_s=0.1)
    fresh_hi.arrival_s = 95.0
    ranked = pol.rank([fresh_hi, old_lo, older_lo], now=100.0,
                      demand_blocks=lambda r: 1)
    # both aged requests jump the urgent fresh one; FIFO among
    # themselves by origin, priority ignored inside the tier
    assert ranked == [older_lo, old_lo, fresh_hi]
    assert pol.promoted(old_lo, 100.0)
    assert not pol.promoted(fresh_hi, 100.0)


def test_demand_blocks_is_cache_aware_and_swap_exact():
    bm = BlockManager(num_blocks=12, block_size=4)
    s = Scheduler(2, bm, 4, 32, policy="slo", prefix_cache=True)
    table = bm.allocate(2)
    bm.register_prefix(np.arange(1, 9), table)
    bm.release(table)                   # cached, zero-ref
    cold = Request(prompt=np.arange(50, 62), max_new_tokens=4)
    warm = Request(prompt=np.concatenate([np.arange(1, 9),
                                          np.array([90, 91, 92, 93])]),
                   max_new_tokens=4)
    assert s._demand_blocks(cold) == 3
    assert s._demand_blocks(warm) == 1  # 2 of 3 blocks served cached
    # the probe is refcount/LRU-neutral: still fully free capacity
    assert bm.num_free + bm.num_cached == bm.num_blocks - 1
    swapped = _req()
    swapped.swap_set = types.SimpleNamespace(n_blocks=5)
    assert s._demand_blocks(swapped) == 5


# -- scheduler-level admission order ----------------------------------------


def _slo_sched(num_slots=2, num_blocks=9, block_size=4, chunk=4,
               max_len=32, aging_s=100.0, **kw):
    return Scheduler(num_slots, BlockManager(num_blocks, block_size),
                     chunk, max_len, policy="slo", aging_s=aging_s, **kw)


def test_slo_admission_orders_by_deadline_not_arrival():
    s = _slo_sched()
    s.policy_now = 10.0
    late = _req(deadline_s=50.0)
    late.arrival_s = 0.0
    mid = _req(deadline_s=20.0)
    mid.arrival_s = 1.0
    tight = _req(deadline_s=5.0)
    tight.arrival_s = 2.0
    for r in (late, mid, tight):
        s.submit(r)
    admitted = s.admit()
    # two slots: the two tightest effective deadlines win, FIFO would
    # have taken (late, mid)
    assert [sl.request is r for sl, r in zip(admitted, (tight, mid))] \
        == [True, True]
    assert late.state == WAITING


def test_smaller_demand_fills_slot_the_frontrunner_cannot():
    # pool: 4 allocatable blocks; resident request holds 2
    s = _slo_sched(num_slots=3, num_blocks=5)
    s.policy_now = 0.0
    resident = _req(prompt_len=8, max_new=4)
    resident.arrival_s = 0.0
    s.submit(resident)
    assert len(s.admit()) == 1
    big = _req(prompt_len=12, max_new=1, deadline_s=1.0)   # needs 3
    big.arrival_s = 0.0
    small = _req(prompt_len=4, max_new=4, deadline_s=9.0)  # needs 1
    small.arrival_s = 0.0
    s.submit(big)
    s.submit(small)
    admitted = s.admit()
    # big ranks first but cannot fit (2 blocks free); slo lets the
    # smaller-demand candidate take the slot — fifo would head-block
    assert [sl.request is small for sl in admitted] == [True]
    assert big.state == WAITING and small.state == PREFILL


def test_aging_promoted_request_blocks_all_younger_admission():
    s = _slo_sched(num_slots=3, num_blocks=5, aging_s=10.0)
    s.policy_now = 0.0
    resident = _req(prompt_len=8, max_new=4)
    resident.arrival_s = 0.0
    s.submit(resident)
    assert len(s.admit()) == 1
    big = _req(prompt_len=12, max_new=1)   # needs 3 > 2 free
    big.arrival_s = 0.0
    small = _req(prompt_len=4, max_new=4, deadline_s=1.0)
    small.arrival_s = 11.0
    s.submit(big)
    s.submit(small)
    s.policy_now = 11.0                    # big has now starved 11s
    assert s.admit() == []                 # strict bound: NOBODY passes
    assert big.aging_promoted and s.aging_promotions == 1
    assert small.state == WAITING
    assert s.blocked_head() is big
    # promotion is counted once, and admission resumes the moment the
    # starving request fits: free the resident's pool share
    s.finish(s.slots[0])
    order = [sl.request for sl in s.admit()]
    assert order == [big, small]
    assert s.aging_promotions == 1


# -- the property test -------------------------------------------------------


def _conserved(bm):
    return (bm.num_free + bm.num_used + bm.num_cached + bm.num_hosted
            == bm.num_blocks - 1)


def _step_host_engine(s, rng=None, preempt_p=0.0):
    """One engine iteration, host-side: admit, instant prefill, decode
    one token per slot, finish at max_new — the scheduler's own
    contract surface, no jax. Returns the slots admitted this call."""
    admitted = s.admit()
    for slot in s.slots:
        if slot.request is not None and slot.request.state == PREFILL:
            s.finish_prefill(slot)
    if rng is not None and preempt_p and rng.rand() < preempt_p:
        busy = [sl for sl in s.slots
                if sl.request is not None and sl.request.state == DECODE]
        if busy:
            s.preempt(busy[rng.randint(len(busy))])
    s.ensure_decode_capacity()
    for slot in s.slots:
        req = slot.request
        if req is None or req.state != DECODE:
            continue
        slot.context_len += 1
        req.output.append(1)
        done = (len(req.prompt) - req.orig_prompt_len
                + len(req.output)) >= req.max_new_tokens
        if done:
            s.finish(slot)
    return admitted


def test_slo_schedule_property_300_steps():
    """Randomized 300-step schedule under ``policy=slo``: submits,
    admissions, natural + injected preemptions, finishes — asserting
    after EVERY step (a) the aging bound: while a promoted (starving)
    request waits, no un-promoted request is admitted past it;
    (b) block conservation; and at the end (c) no starvation: every
    request finishes with its exact token count, pool drained."""
    rng = np.random.RandomState(0)
    s = _slo_sched(num_slots=3, num_blocks=13, block_size=4, chunk=4,
                   max_len=32, aging_s=0.6)
    t = 0.0
    everyone = []
    for step in range(300):
        t += 0.05
        s.policy_now = t
        if len(everyone) < 60 and rng.rand() < 0.35:
            r = Request(
                prompt=rng.randint(1, 100, (rng.randint(1, 13),)),
                max_new_tokens=int(rng.randint(1, 9)),
                priority=int(rng.randint(0, 3)),
                deadline_s=(float(rng.uniform(0.2, 5.0))
                            if rng.rand() < 0.7 else None))
            r.arrival_s = t
            s.submit(r)
            everyone.append(r)
        admitted = _step_host_engine(s, rng, preempt_p=0.05)
        if any(r.aging_promoted for r in s.waiting):
            assert all(sl.request.aging_promoted for sl in admitted), \
                f"step {step}: younger work queue-jumped a starving " \
                "request"
        assert _conserved(s.blocks), f"step {step}: blocks leaked"
    # drain: no new work, everything must complete (liveness)
    for step in range(2000):
        if not s.has_work():
            break
        t += 0.05
        s.policy_now = t
        _step_host_engine(s)
        assert _conserved(s.blocks)
    assert not s.has_work(), "schedule never drained: starvation"
    assert everyone and all(r.state == FINISHED for r in everyone)
    for r in everyone:
        got = len(r.prompt) - r.orig_prompt_len + len(r.output)
        assert got == r.max_new_tokens, \
            f"request {r.rid}: {got} tokens != {r.max_new_tokens}"
    assert s.blocks.num_used == 0
    assert s.aging_promotions == sum(
        1 for r in everyone if r.aging_promoted)


# -- schema: typed riders, mistyped rows rejected ----------------------------


def test_schema_types_policy_riders_and_rejects_mistypes():
    base = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
            "event": "finish", "request": 3, "deadline_s": 0.5,
            "priority": 1, "deadline_miss": False}
    assert validate_event(base) == []
    limited = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
               "event": "rate_limited", "group": "t0",
               "retry_after_s": 0.25, "rate_limited": 2}
    assert validate_event(limited) == []
    report = dict(base, event="report", policy="slo",
                  aging_promotions=4, deadline_miss_frac=0.25,
                  priority_slo_attainment={"0": 1.0, "1": 0.5})
    assert validate_event(report) == []
    for field, bad in [("deadline_s", "soon"), ("priority", 1.5),
                       ("priority", True), ("deadline_miss", "no"),
                       ("rate_limited", 0.5), ("retry_after_s", "later"),
                       ("policy", 7), ("aging_promotions", "many"),
                       ("deadline_miss_frac", "low"),
                       ("priority_slo_attainment", [1.0])]:
        row = dict(report, **{field: bad})
        errs = validate_event(row)
        assert errs and field in errs[0], (field, bad, errs)


# -- engine + router end-to-end (jax) ----------------------------------------


@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


_ENGINE_KW = dict(num_slots=2, block_size=4, num_blocks=20,
                  prefill_chunk=8, max_model_len=64)

_POLICY_FIELDS = {"policy", "deadline_s", "priority", "deadline_miss",
                  "rate_limited", "retry_after_s", "aging_promotions",
                  "deadline_miss_frac", "priority_slo_attainment"}


def _serve_events(model, params, trace, out_dir, **engine_kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    obs.reset(out_dir=str(out_dir), enabled=True)
    try:
        eng = ServeEngine(model, params, **engine_kw)
        reqs = [eng.submit(p, m) for p, m in trace]
        eng.run()
        outs = [list(eng.output_ids(r)) for r in reqs]
        summary = eng.slo_summary()
        obs.flush()
    finally:
        obs.reset()
    events = [e for _, e, err in
              obs.iter_events(str(out_dir / "events.jsonl"))
              if err is None and e["type"] == "serve"]
    return events, outs, summary


def test_fifo_event_stream_identical_to_default_engine(gpt2_setup,
                                                       tmp_path):
    """The byte-identity contract: ``policy="fifo"`` IS the pre-ISSUE
    -20 engine. Same trace through a default-built engine and an
    explicit fifo one → the serve-event streams carry the same events
    with the same field sets in the same order, token-identical
    outputs, and ZERO ISSUE-20 fields anywhere (events or summary)."""
    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(7)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(5, 6), (11, 4), (7, 8), (4, 5)]]
    ev_a, outs_a, sum_a = _serve_events(model, params, trace,
                                        tmp_path / "default",
                                        **_ENGINE_KW)
    ev_b, outs_b, sum_b = _serve_events(model, params, trace,
                                        tmp_path / "fifo",
                                        policy="fifo", **_ENGINE_KW)
    shape_a = [(e["event"], tuple(sorted(set(e) - {"request", "t"})))
               for e in ev_a]
    shape_b = [(e["event"], tuple(sorted(set(e) - {"request", "t"})))
               for e in ev_b]
    assert shape_a == shape_b
    assert outs_a == outs_b
    for events, summary in ((ev_a, sum_a), (ev_b, sum_b)):
        hit = [k for e in events for k in e if k in _POLICY_FIELDS]
        assert not hit, f"fifo stream leaked policy fields: {hit}"
        assert not (_POLICY_FIELDS & set(summary))


def test_slo_engine_emits_riders_and_valid_events(gpt2_setup, tmp_path):
    """policy=slo with deadlines/priorities: tokens still identical to
    fifo (the WHO-not-WHAT contract), finish events carry the
    deadline verdicts, the summary carries the gated rollups, and the
    whole stream passes the schema validator."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.loadgen import (
        SloSpec,
    )

    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(9)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(6, 5), (9, 6), (5, 4), (12, 7)]]
    base_ev, base_outs, _ = _serve_events(model, params, trace,
                                          tmp_path / "base", **_ENGINE_KW)
    out = tmp_path / "slo"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        eng = ServeEngine(model, params, policy="slo", aging_s=60.0,
                          **_ENGINE_KW)
        slo = SloSpec(ttft_s=30.0)
        reqs = [eng.submit(p, m, deadline_s=(1e-9 if i % 2 else 1e6),
                           priority=i % 2, slo=slo)
                for i, (p, m) in enumerate(trace)]
        eng.run()
        outs = [list(eng.output_ids(r)) for r in reqs]
        summary = eng.slo_summary()
        obs.flush()
    finally:
        obs.reset()
    assert sorted(map(tuple, outs)) == sorted(map(tuple, base_outs))
    assert summary["policy"] == "slo"
    assert summary["deadline_miss_frac"] == 0.5
    assert set(summary["priority_slo_attainment"]) == {"0", "1"}
    assert [r.deadline_miss for r in reqs] == [False, True] * 2
    count, errors = obs.validate_events_file(str(out / "events.jsonl"))
    assert not errors and count > 0
    serve_ev = [e for _, e, err in
                obs.iter_events(str(out / "events.jsonl"))
                if err is None and e["type"] == "serve"]
    submits = [e for e in serve_ev if e.get("event") == "submit"]
    finishes = [e for e in serve_ev if e.get("event") == "finish"]
    assert len(finishes) == len(trace)
    # deadline_s rides the submit event, the verdict rides finish
    assert all("deadline_s" in e for e in submits)
    assert all("deadline_miss" in e for e in finishes)
    assert sum(e.get("priority", 0) for e in submits) == 2


def test_router_rate_limit_structured_rejection(gpt2_setup, tmp_path):
    """An empty tenant bucket rejects STRUCTURALLY: the submit returns
    :class:`RateLimited` with the bucket's own retry estimate, the
    rejection is counted in the fleet summary, and un-metered groups
    pass untouched — never a silent drop, never an exception."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.router import (
        Router,
    )

    _cfg, model, params = gpt2_setup
    rng = np.random.RandomState(11)
    out = tmp_path / "router"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        router = Router(model, params, replicas=1,
                        rate_limit={"metered": (0.5, 2)}, **_ENGINE_KW)
        reqs = [router.submit(rng.randint(1, 120, (5,)).astype(np.int32),
                              4, group="metered", arrival_s=0.0)
                for _ in range(4)]
        free = router.submit(rng.randint(1, 120, (5,)).astype(np.int32),
                             4, group="unmetered", arrival_s=0.0)
        router.run()
        summary = router.slo_summary()
        obs.flush()
    finally:
        obs.reset()
    limited = [r for r in reqs if getattr(r, "rejected", False)]
    served = [r for r in reqs if not getattr(r, "rejected", False)]
    assert len(limited) == 2 and len(served) == 2   # burst=2
    assert all(isinstance(r, RateLimited) for r in limited)
    # virtual clock pinned at 0: retry = one token at 0.5 tok/s
    assert all(r.retry_after_s == pytest.approx(2.0) for r in limited)
    assert all(r.group == "metered" for r in limited)
    assert not getattr(free, "rejected", False)
    assert summary["rate_limited"] == 2
    assert all(r.state == FINISHED for r in served + [free])
    events = [e for _, e, err in
              obs.iter_events(str(out / "events.jsonl"))
              if err is None and e["type"] == "serve"
              and e.get("event") == "rate_limited"]
    assert len(events) == 2
    assert all(e["group"] == "metered" and e["retry_after_s"] > 0
               for e in events)
    count, errors = obs.validate_events_file(str(out / "events.jsonl"))
    assert not errors
