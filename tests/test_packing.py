"""Token packing (ISSUE 2 tentpole #2): pack_examples layout invariants,
the cross-contamination-safe segment mask, and — the acceptance gate —
packed-batch loss/accuracy EXACTLY matching unpacked on the same
examples for causal-lm (GPT-2) and MLM-shaped (BERT) training."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
    ArrayDataset,
    ShardedBatcher,
    pack_examples,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    make_segment_mask,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
    causal_lm_loss,
    token_cls_loss,
)


def _ragged_lm_columns(n=24, width=32, vocab=120, seed=0):
    """Causal-LM shaped columns with ragged real lengths (labels = ids,
    -100 on padding — what from_lm_texts(packed=False) produces)."""
    rng = np.random.RandomState(seed)
    ids = np.zeros((n, width), np.int32)
    mask = np.zeros((n, width), np.int32)
    lengths = rng.randint(3, width // 2 + 1, size=n)
    for i, L in enumerate(lengths):
        ids[i, :L] = rng.randint(3, vocab, size=L)
        mask[i, :L] = 1
    labels = np.where(mask > 0, ids, -100).astype(np.int32)
    return {"input_ids": ids, "attention_mask": mask, "labels": labels}


# -- layout invariants -------------------------------------------------------

def test_pack_examples_layout_and_determinism():
    cols = _ragged_lm_columns()
    packed = pack_examples(cols, max_length=32, causal=True)
    n_tokens = int((cols["attention_mask"] > 0).sum())
    # every real token survives, none duplicated
    assert int(packed["attention_mask"].sum()) == n_tokens
    # pad waste collapses vs one-example-per-row
    assert packed["attention_mask"].mean() > cols["attention_mask"].mean()
    assert packed["input_ids"].shape[0] < cols["input_ids"].shape[0]
    # segment ids: 1-based per example, 0 on padding, contiguous runs
    seg = packed["segment_ids"]
    assert ((seg == 0) == (packed["attention_mask"] == 0)).all()
    # positions restart at 0 within each segment
    pos = packed["position_ids"]
    for r in range(seg.shape[0]):
        for s in range(1, seg[r].max() + 1):
            span = pos[r][seg[r] == s]
            np.testing.assert_array_equal(span, np.arange(len(span)))
            # causal=True: the segment's first token carries no label
            assert packed["labels"][r][seg[r] == s][0] == -100
    # deterministic: same input, same packing
    again = pack_examples(cols, max_length=32, causal=True)
    for k in packed:
        np.testing.assert_array_equal(packed[k], again[k])


def test_pack_examples_rejects_scalar_columns_and_oversize():
    cols = _ragged_lm_columns()
    with pytest.raises(ValueError, match="token columns"):
        pack_examples({**cols, "labels": np.zeros(len(cols["input_ids"]),
                                                  np.int32)}, 32)
    with pytest.raises(ValueError, match="exceeds"):
        pack_examples(cols, max_length=8)


def test_sharded_batcher_pack_mode():
    mesh = build_mesh(MeshConfig())
    ds = ArrayDataset(_ragged_lm_columns())
    b = ShardedBatcher(ds, 2, mesh, shuffle=False, pack=True,
                       pack_causal=True, process_index=0, process_count=1)
    batch = next(iter(b.local_batches(0)))
    assert "segment_ids" in batch and "position_ids" in batch
    assert (batch["segment_ids"].max(axis=1) > 1).any()  # rows really share
    with pytest.raises(ValueError, match="pick one"):
        ShardedBatcher(ds, 2, mesh, pack=True, bucket_sizes=[16, 32],
                       process_index=0, process_count=1)


def test_mlm_dataset_pack_requires_static_masking():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset as DS,
        WordHashTokenizer,
    )

    tok = WordHashTokenizer(vocab_size=512)
    texts = [f"doc {i} " + "word " * (3 + i % 5) for i in range(12)]
    with pytest.raises(ValueError, match="static_masking"):
        DS.from_mlm_texts(tok, texts, max_length=24).pack(48)
    packed = DS.from_mlm_texts(tok, texts, max_length=24,
                               static_masking=True).pack(48)
    assert "segment_ids" in packed.columns
    assert (packed.columns["segment_ids"].max(axis=1) > 1).any()


def test_segment_mask_blocks_cross_example_attention():
    seg = jnp.asarray([[1, 1, 2, 2, 0]])
    m = np.asarray(make_segment_mask(seg))[0, 0]
    keep = m == 0.0
    expect = np.array([
        [1, 1, 0, 0, 0],
        [1, 1, 0, 0, 0],
        [0, 0, 1, 1, 0],
        [0, 0, 1, 1, 0],
        [0, 0, 0, 0, 0],   # padding attends nothing (loss-masked anyway)
    ], bool)
    np.testing.assert_array_equal(keep, expect)


# -- loss equivalence (the acceptance gate) ----------------------------------

def _sums(loss_fn, model, params, batch):
    _, sums = loss_fn(model.apply, params,
                      {k: jnp.asarray(v) for k, v in batch.items()},
                      {}, False)
    return {k: float(v) for k, v in jax.device_get(sums).items()}


def test_packed_causal_lm_loss_matches_unpacked():
    """Same examples, packed vs one-per-row: identical loss_sum, correct
    count and token count — per-example metrics stay exact (the
    cross-contamination-safe mask + per-segment positions + boundary
    label masking together)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=120, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     intermediate_size=64, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg, seed=0)
    cols = _ragged_lm_columns(n=16, width=24, vocab=120, seed=3)
    packed = pack_examples(cols, max_length=48, causal=True)
    ref = _sums(causal_lm_loss, model, params, cols)
    got = _sums(causal_lm_loss, model, params, packed)
    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["loss_sum"], ref["loss_sum"],
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(got["correct"], ref["correct"])


def test_packed_mlm_loss_matches_unpacked():
    """MLM-shaped packing (no shift): sparse labels survive packing and
    the masked sums agree with the unpacked batch."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForMaskedLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
        EncoderConfig,
    )

    cfg = EncoderConfig(vocab_size=120, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=64,
                        hidden_dropout=0.0, attention_dropout=0.0)
    model = BertForMaskedLM(cfg)
    params = init_params(model, cfg, seed=1)
    cols = _ragged_lm_columns(n=16, width=24, vocab=120, seed=5)
    # sparse MLM-style labels: supervise ~20% of real tokens
    rng = np.random.RandomState(7)
    supervise = (cols["attention_mask"] > 0) & (rng.rand(16, 24) < 0.2)
    cols["labels"] = np.where(supervise, cols["input_ids"], -100).astype(
        np.int32)
    packed = pack_examples(cols, max_length=48)
    import functools
    mlm_loss = functools.partial(token_cls_loss, with_f1=False)
    ref = _sums(mlm_loss, model, params, cols)
    got = _sums(mlm_loss, model, params, packed)
    assert got["count"] == ref["count"]
    np.testing.assert_allclose(got["loss_sum"], ref["loss_sum"],
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(got["correct"], ref["correct"])


def test_packed_train_step_end_to_end():
    """A full jitted train step on a packed batcher runs and produces a
    finite loss with the segment/position columns flowing through the
    trainer's apply plumbing."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
        TrainConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import (
        Trainer,
    )

    mesh = build_mesh(MeshConfig())
    cfg = Gpt2Config(vocab_size=120, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     intermediate_size=64)
    model = Gpt2LMHeadModel(cfg)
    params = init_params(model, cfg, seed=0)
    tc = TrainConfig(task="causal-lm", segment_packing=True,
                     train_batch_size=2, log_every_steps=0)
    trainer = Trainer(tc, model, params, mesh)
    # enough short examples that packing still leaves >= one global batch
    # of rows (the test mesh is 8-way data parallel)
    ds = ArrayDataset(_ragged_lm_columns(n=160, width=24, vocab=120))
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=False, pack=True,
                             pack_causal=True, process_index=0,
                             process_count=1)
    history = trainer.fit(batcher, epochs=1)
    assert np.isfinite(history["loss"][0])
