"""Tokenization interface tests (reference contract: input_ids +
attention_mask, static [N, max_length] shapes — scripts/train.py:75-83)."""

import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
    WordHashTokenizer,
    load_tokenizer,
)


def test_shapes_and_mask():
    tok = WordHashTokenizer(vocab_size=1000)
    out = tok(["hello world", "a much longer sentence with more words"],
              max_length=16)
    assert out["input_ids"].shape == (2, 16)
    assert out["attention_mask"].shape == (2, 16)
    assert out["attention_mask"][0].sum() == 4  # CLS hello world SEP
    assert out["input_ids"][0, 0] == tok.cls_token_id
    # padding is pad_token_id where mask is 0
    assert (out["input_ids"][out["attention_mask"] == 0] == tok.pad_token_id).all()


def test_determinism_across_instances():
    a = WordHashTokenizer()(["some review text"], max_length=8)
    b = WordHashTokenizer()(["some review text"], max_length=8)
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])


def test_truncation():
    tok = WordHashTokenizer()
    out = tok(["w " * 100], max_length=10)
    assert out["input_ids"].shape == (1, 10)
    assert out["attention_mask"].sum() == 10


def test_padding_longest():
    tok = WordHashTokenizer()
    out = tok(["a b", "a b c d"], padding="longest", max_length=512)
    assert out["input_ids"].shape[1] == 6  # CLS a b c d SEP


def test_text_pairs():
    tok = WordHashTokenizer()
    out = tok(["question here"], text_pairs=["context here"], max_length=16)
    # CLS q here SEP c here SEP = 7 tokens
    assert out["attention_mask"][0].sum() == 7
    # segment ids: 0 for first sentence incl. its SEP, 1 for the pair
    np.testing.assert_array_equal(out["token_type_ids"][0][:7],
                                  [0, 0, 0, 0, 1, 1, 1])


def test_save_load_roundtrip(tmp_path):
    tok = WordHashTokenizer(vocab_size=555)
    tok.save_pretrained(str(tmp_path))
    tok2 = load_tokenizer(str(tmp_path))
    assert isinstance(tok2, WordHashTokenizer)
    assert tok2.vocab_size == 555
    a = tok(["same text"], max_length=8)
    b = tok2(["same text"], max_length=8)
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])


def test_fallback_for_missing_dir():
    tok = load_tokenizer("not-a-local-dir-hub-name")
    assert isinstance(tok, WordHashTokenizer)
