"""bf16 training-quality evidence (SURVEY.md §7 hard-part 5, VERDICT r1
next-steps #7): the default TPU compute dtype must not cost accuracy.

Trains the synthetic seq-cls config twice from the same init — fp32
compute vs bf16 compute (params/optimizer state stay fp32 in both, the
framework default) — and asserts the final train accuracy lands within
2 points and eval accuracy within 3.

Why this holds (the fp32 islands that make bf16 safe here):
- attention logits + softmax in fp32 on every path — xla
  (``ops/attention.py:34``), Pallas flash (fp32 logits and
  running-max/sum scratch, ``ops/pallas_attention.py``), ring;
- layernorm statistics in fp32 (``models/layers.py::_layernorm``);
- loss, metrics, and the cross-entropy logits cast up to fp32
  (``train/trainer.py:72-75``);
- Adam moments and params in fp32 (``param_dtype``), so bf16 touches
  only activations/matmuls — the MXU-native part.
"""

import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 32
VOCAB = 512


def _run(dtype: str, devices):
    mesh = build_mesh(MeshConfig(), devices=devices)
    enc = EncoderConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ,
                        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    model = BertForSequenceClassification(enc, num_labels=2)
    params = init_params(model, enc, seed=0)
    cfg = TrainConfig(epochs=3, dtype=dtype, learning_rate=1e-3,
                      scale_lr_by_world_size=False, log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=VOCAB)
    texts, labels = synthetic_text_classification(256, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))

    etexts, elabels = synthetic_text_classification(128, seed=1)
    eds = ArrayDataset.from_texts(tok, etexts, elabels, max_length=SEQ)
    emetrics = trainer.evaluate(
        ShardedBatcher(eds, 16, mesh, shuffle=False, seed=0,
                       drop_remainder=False))
    return (hist["sparse_categorical_accuracy"][-1],
            emetrics["eval_accuracy"])


def test_bf16_matches_fp32_accuracy(devices8):
    train32, eval32 = _run("float32", devices8[:1])
    train16, eval16 = _run("bfloat16", devices8[:1])
    # both must actually learn, and bf16 must land within 2 train-accuracy
    # points / 3 eval points of fp32
    assert train32 > 0.8 and train16 > 0.8
    assert abs(train16 - train32) <= 0.02
    assert abs(eval16 - eval32) <= 0.03
