"""bf16 training-quality evidence (SURVEY.md §7 hard-part 5, VERDICT r1
next-steps #7): the default TPU compute dtype must not cost accuracy.

Trains the synthetic seq-cls config twice from the same init — fp32
compute vs bf16 compute (params/optimizer state stay fp32 in both, the
framework default) — and asserts the final train accuracy lands within
2 points and eval accuracy within 3.

Why this holds (the fp32 islands that make bf16 safe here):
- attention logits + softmax in fp32 on every path — xla
  (``ops/attention.py:34``), Pallas flash (fp32 logits and
  running-max/sum scratch, ``ops/pallas_attention.py``), ring;
- layernorm statistics in fp32 (``models/layers.py::_layernorm``);
- loss, metrics, and the cross-entropy logits cast up to fp32
  (``train/trainer.py:72-75``);
- Adam moments and params in fp32 (``param_dtype``), so bf16 touches
  only activations/matmuls — the MXU-native part.
"""

import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 32
VOCAB = 512


def _run(dtype: str, devices):
    mesh = build_mesh(MeshConfig(), devices=devices)
    enc = EncoderConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ,
                        dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    model = BertForSequenceClassification(enc, num_labels=2)
    params = init_params(model, enc, seed=0)
    cfg = TrainConfig(epochs=3, dtype=dtype, learning_rate=1e-3,
                      scale_lr_by_world_size=False, log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=VOCAB)
    texts, labels = synthetic_text_classification(256, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))

    etexts, elabels = synthetic_text_classification(128, seed=1)
    eds = ArrayDataset.from_texts(tok, etexts, elabels, max_length=SEQ)
    emetrics = trainer.evaluate(
        ShardedBatcher(eds, 16, mesh, shuffle=False, seed=0,
                       drop_remainder=False))
    return (hist["sparse_categorical_accuracy"][-1],
            emetrics["eval_accuracy"])


def test_bf16_matches_fp32_accuracy(devices8):
    train32, eval32 = _run("float32", devices8[:1])
    train16, eval16 = _run("bfloat16", devices8[:1])
    # both must actually learn, and bf16 must land within 2 train-accuracy
    # points / 3 eval points of fp32
    assert train32 > 0.8 and train16 > 0.8
    assert abs(train16 - train32) <= 0.02
    assert abs(eval16 - eval32) <= 0.03


def test_lowp_adam_step_matches_fp32_adam():
    """scale_by_adam_lowp computes the identical update to optax's fp32
    Adam up to the bf16 rounding of what was STORED between steps: a few
    steps on a toy quadratic stay within bf16-mantissa tolerance, and
    the stored state really is bf16 (the memory claim)."""
    import jax
    import numpy as np
    import optax

    from huggingface_sagemaker_tensorflow_distributed_tpu.train.optim import (
        scale_by_adam_lowp,
    )

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(16, 8),
                               jnp.float32)}
    ref = optax.scale_by_adam()
    lowp = scale_by_adam_lowp()
    s_ref = ref.init(params)
    s_lowp = lowp.init(params)
    assert s_lowp.mu["w"].dtype == jnp.bfloat16
    assert s_lowp.nu["w"].dtype == jnp.bfloat16
    rng = np.random.RandomState(1)
    for step in range(5):
        g = {"w": jnp.asarray(rng.randn(16, 8) * 0.1, jnp.float32)}
        u_ref, s_ref = ref.update(g, s_ref)
        u_lowp, s_lowp = lowp.update(g, s_lowp)
        assert u_lowp["w"].dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(u_lowp["w"]),
                                   np.asarray(u_ref["w"]),
                                   rtol=2e-2, atol=2e-3,
                                   err_msg=f"step {step}")


def _run_state_dtype(state_dtype: str, devices):
    mesh = build_mesh(MeshConfig(), devices=devices)
    enc = EncoderConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ)
    model = BertForSequenceClassification(enc, num_labels=2)
    params = init_params(model, enc, seed=0)
    cfg = TrainConfig(epochs=3, dtype="float32", learning_rate=1e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      optimizer_state_dtype=state_dtype)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=VOCAB)
    texts, labels = synthetic_text_classification(256, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    return hist["sparse_categorical_accuracy"][-1]


def test_bf16_optimizer_state_quality(devices8):
    """bf16 m/v storage (--optimizer_state_dtype bfloat16, the optimizer
    HBM halver) must train to the same place as fp32 state — the same
    2-point bar the compute-dtype test holds bf16 matmuls to."""
    acc32 = _run_state_dtype("float32", devices8[:1])
    acc16 = _run_state_dtype("bfloat16", devices8[:1])
    assert acc32 > 0.8 and acc16 > 0.8
    assert abs(acc16 - acc32) <= 0.02
