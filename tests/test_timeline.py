"""Request-lifecycle tracing (ISSUE 10): the tier-1 decomposition gate
— a REAL engine run's ``request_timeline`` events must decompose each
request's e2e into queue + prefill + decode + preempted + overhead
within tolerance, with the accounting entirely host-side (the serve
bench's compile-flatness gates run with the timeline on, so zero new
compiled variants is enforced there) — plus the jax-less
``obs/timeline.py`` tooling: sliding-window percentile estimator,
incremental tail follower (never re-reads the prefix), deterministic
``obsctl timeline|slo`` output, and the poisoned-jax import contract
extended over all of it.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
    SlidingWindow,
    TailFollower,
    TailStats,
    check_decomposition,
    chrome_trace,
    collect_timelines,
    gantt_text,
    slo_attribution,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBSCTL = os.path.join(_REPO, "scripts", "obsctl.py")


# -- synthetic records (pure host, no jax) ------------------------------------

def _tl_event(rid, t=1000.0, at="finish", group="", q=0.3, pf=0.1,
              dc=0.5, pe=0.0, oh=0.1, bucket=64, **extra):
    """One schema-valid request_timeline event whose segments agree
    with its aggregates by construction."""
    e2e = q + pf + dc + pe + oh
    segs = [{"ph": "queue", "t0": 0.0, "dur": q}]
    cursor = q
    if pe:
        segs.append({"ph": "preempted", "t0": cursor, "dur": pe})
        cursor += pe
    segs.append({"ph": "prefill", "t0": cursor, "dur": pf,
                 "from": 0, "chunks": 1})
    cursor += pf
    segs.append({"ph": "decode", "t0": cursor + oh, "dur": dc,
                 "bucket": bucket, "iters": 10, "tokens": 10})
    ev = {"v": 1, "t": t, "host": 0, "pid": 1, "type": "serve",
          "event": "request_timeline", "request": rid, "at": at,
          "e2e_s": round(e2e, 6), "queue_s": q, "prefill_s": pf,
          "decode_s": dc, "preempted_s": pe, "overhead_s": round(oh, 6),
          "tokens": 10, "prompt_len": 5, "preemptions": 1 if pe else 0,
          "segments": segs, "ttft_s": round(q + pf, 6)}
    if group:
        ev["group"] = group
    ev.update(extra)
    return ev


def _ledger_event(i, t=1000.0, tokens=4, dur=0.05, waiting=2,
                  kv=0.5):
    return {"v": 1, "t": t, "host": 0, "pid": 1, "type": "serve",
            "event": "iteration_ledger", "iteration": i,
            "dur_s": dur, "prefill_s": 0.01, "decode_s": 0.03,
            "gather_bucket": 64, "prefill_chunks": 1,
            "prefill_dispatches": 1, "decode_slots": 3,
            "tokens": tokens, "waiting": waiting, "kv_used_frac": kv}


def _write_events(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


# -- sliding-window estimator -------------------------------------------------

def test_sliding_window_percentile_exact_and_evicting():
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
        percentile,
    )

    win = SlidingWindow(5)
    assert win.percentile(0.5) is None and win.mean() is None
    vals = [5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0]
    for i, v in enumerate(vals):
        win.push(v)
        expect = sorted(vals[max(0, i - 4):i + 1])
        # exact nearest-rank over the CURRENT window, same convention
        # as obs.report.percentile — no sketch error anywhere
        for p in (0.0, 0.5, 0.95, 0.99, 1.0):
            assert win.percentile(p) == percentile(expect, p)
    assert len(win) == 5
    assert win.sum() == pytest.approx(sum(vals[-5:]))
    # duplicates evict correctly (bisect_left removes ONE copy)
    dup = SlidingWindow(3)
    for v in (2.0, 2.0, 2.0, 4.0):
        dup.push(v)
    assert len(dup) == 3 and dup.percentile(1.0) == 4.0
    with pytest.raises(ValueError):
        SlidingWindow(0)


# -- tail follower ------------------------------------------------------------

def test_tail_follower_reads_appends_only(tmp_path):
    path = str(tmp_path / "events.jsonl")
    e1, e2, e3 = (_ledger_event(i, t=1000.0 + i) for i in range(3))
    _write_events(path, [e1])
    fol = TailFollower(path)
    events, errors = fol.poll()
    assert not errors and [e["iteration"] for e in events] == [0]
    # nothing new: empty poll
    assert fol.poll() == ([], [])
    # append one complete + one PARTIAL line: only the complete one is
    # consumed; the partial stays unconsumed until its newline lands
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(e2) + "\n")
        f.write(json.dumps(e3)[:20])
    events, errors = fol.poll()
    assert not errors and [e["iteration"] for e in events] == [1]
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(e3)[20:] + "\n")
    events, errors = fol.poll()
    assert not errors and [e["iteration"] for e in events] == [2]


def test_tail_follower_never_rereads_prefix(tmp_path):
    """The incremental contract, observable: after a poll, clobber the
    already-consumed prefix bytes in place — if the follower ever
    seeks back it would now see garbage, so a clean second poll PROVES
    the prefix is not re-read."""
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [_ledger_event(0)])
    fol = TailFollower(path)
    events, errors = fol.poll()
    assert not errors and len(events) == 1
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.write(b"x" * (size - 1))       # torch the consumed prefix
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(_ledger_event(1)) + "\n")
    events, errors = fol.poll()
    assert not errors and [e["iteration"] for e in events] == [1]


def test_tail_follower_flags_truncation(tmp_path):
    """A recreated/truncated file below the consumed offset must fail
    loud — silence would read as an idle engine forever."""
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [_ledger_event(0), _ledger_event(1)])
    fol = TailFollower(path)
    events, errors = fol.poll()
    assert not errors and len(events) == 2
    _write_events(path, [_ledger_event(2)])      # recreated, shorter
    events, errors = fol.poll()
    assert not events and errors
    assert "truncated" in errors[0]


def test_tail_follower_flags_malformed_complete_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [_ledger_event(0)])
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"not json\n')
    fol = TailFollower(path)
    events, errors = fol.poll()
    assert len(events) == 1 and errors
    assert "unparseable" in errors[0]


def test_tail_stats_rolls_ledger_and_ttft():
    stats = TailStats(window=4)
    for i in range(6):
        stats.update(_ledger_event(i, tokens=4, dur=0.5, waiting=i,
                                   kv=0.1 * i))
    first = {"v": 1, "t": 1.0, "host": 0, "pid": 1, "type": "serve",
             "event": "first_token", "request": 0, "ttft_s": 0.25}
    stats.update(first)
    assert stats.waiting == 5 and stats.iteration == 5
    assert stats.kv_used_frac == pytest.approx(0.5)
    line = stats.render()
    # windowed tokens/sec: 4 ledgers * 4 tokens / (4 * 0.5s) = 8.0
    assert "tok/s=8.0" in line and "ttft_p50_s=0.25" in line


def test_tail_stats_rolling_slo_attainment():
    """ISSUE 16: verdict-carrying finish events roll a windowed
    attainment column into the tail line; closed-loop streams (no
    verdicts) keep their exact pre-16 rendering — the column is
    absent, not 'slo_attainment=-'."""
    def _finish(rid, met):
        return {"v": 1, "t": 1000.0 + rid, "host": 0, "pid": 1,
                "type": "serve", "event": "finish", "request": rid,
                "tokens": 4, "preemptions": 0, "slo_met": met}

    closed = TailStats(window=4)
    closed.update(_ledger_event(0))
    assert "slo_attainment" not in closed.render()
    # a finish WITHOUT a verdict (closed-loop) keeps the column absent
    no_verdict = _finish(1, True)
    del no_verdict["slo_met"]
    closed.update(no_verdict)
    assert "slo_attainment" not in closed.render()
    # a mistyped verdict is ignored, not crashed on or miscounted
    closed.update({**_finish(2, True), "slo_met": "yes"})
    assert "slo_attainment" not in closed.render()

    stats = TailStats(window=4)
    for rid, met in enumerate([True, True, False, True]):
        stats.update(_finish(rid, met))
    assert "slo_attainment=0.750" in stats.render()
    # the window ROLLS: four more hits evict the miss entirely
    for rid in range(4, 8):
        stats.update(_finish(rid, True))
    assert "slo_attainment=1.000" in stats.render()


def test_cli_tail_renders_attainment_column(tmp_path):
    """The live view of the same column: one poll over a stream whose
    finishes carry verdicts prints it, rc 0."""
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [
        _ledger_event(0),
        {"v": 1, "t": 1001.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 0, "tokens": 4,
         "preemptions": 0, "slo_met": True},
        {"v": 1, "t": 1002.0, "host": 0, "pid": 1, "type": "serve",
         "event": "finish", "request": 1, "tokens": 4,
         "preemptions": 0, "slo_met": False},
    ])
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "tail", path, "--updates", "1",
         "--interval", "0.05"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    assert proc.returncode == 0, proc.stderr
    assert "slo_attainment=0.500" in proc.stdout


# -- decomposition checker / attribution over synthetic records ---------------

def test_check_decomposition_accepts_consistent_and_names_bugs():
    good = _tl_event(0)
    assert check_decomposition(good) == []
    # a double-attributed dispatch: decode_s inflated past what e2e
    # can hold -> negative overhead -> phase sum breaks
    bad = _tl_event(1, dc=5.0, oh=0.1)
    bad["e2e_s"] = 1.0
    bad["overhead_s"] = round(1.0 - (0.3 + 0.1 + 5.0), 6)
    assert any("negative overhead" in e or "phase sum" in e
               or "outside" in e for e in check_decomposition(bad))
    # segments disagreeing with the aggregates
    drift = _tl_event(2)
    drift["segments"][-1]["dur"] = 0.01
    assert any("decode segments sum" in e
               for e in check_decomposition(drift))
    # mistyped field
    broken = _tl_event(3)
    broken["queue_s"] = None
    assert check_decomposition(broken)


def test_collect_timelines_keys_by_process_and_request():
    """Request ids are per-process counters: a multi-host merge AND a
    same-host restart (two runs appended into one events.jsonl — two
    os pids, both host 0) must keep each process's rid 0 as a DISTINCT
    record; the Chrome trace separates processes as viewer-pid rows."""
    a = _tl_event(0, t=1000.0, group="h0")
    b = _tl_event(0, t=1001.0, group="h1")
    b["host"] = 1
    c = _tl_event(0, t=1002.0, group="h0-run2")
    c["pid"] = 2                         # same host, restarted process
    recs = collect_timelines([a, b, c])
    assert len(recs) == 3
    assert [(r.get("host", 0), r["pid"], r["request"])
            for r in recs] == [(0, 1, 0), (0, 2, 0), (1, 1, 0)]
    doc = chrome_trace(recs)
    assert {(e["pid"], e["tid"]) for e in doc["traceEvents"]} == \
        {(0, 0), (1, 0), (2, 0)}         # 3 distinct viewer rows
    assert all(e["args"]["host"] in (0, 1)
               for e in doc["traceEvents"])
    text = gantt_text(recs)
    assert "h0:p1:r0" in text and "h0:p2:r0" in text \
        and "h1:p1:r0" in text


def test_collect_timelines_last_event_wins_any_order():
    pre = _tl_event(7, t=1000.0, at="preempt", dc=0.0, pe=0.0)
    fin = _tl_event(7, t=1002.0, at="finish", pe=0.2)
    other = _tl_event(3, t=1001.0)
    for order in ([pre, fin, other], [fin, other, pre],
                  [other, pre, fin]):
        recs = collect_timelines(order)
        assert [r["request"] for r in recs] == [3, 7]
        assert recs[1]["at"] == "finish"
        assert recs[1]["preempted_s"] == pytest.approx(0.2)


def test_slo_attribution_names_dominant_phase_and_groups():
    # nine fast decode-dominated requests, one tail request that burned
    # its budget queued — the attribution must say "queue", not just
    # "p99 is high"
    events = [_tl_event(i, group="fast", dc=0.5 + 0.05 * i)
              for i in range(9)]
    events.append(_tl_event(9, group="slow", q=9.0, ttft_s=9.4))
    doc = slo_attribution(collect_timelines(events), pct=0.95)
    assert doc["requests"] == 10
    assert doc["tail"]["count"] == 1
    assert doc["tail"]["dominant_phase_counts"] == {"queue": 1}
    assert doc["tail"]["requests"][0]["request"] == 9
    assert doc["tail"]["requests"][0]["dominant_phase"] == "queue"
    # per-group rollup (the per-tenant hook): the slow group's p99
    # stands apart from the fast one's
    assert set(doc["groups"]) == {"fast", "slow"}
    assert doc["groups"]["slow"]["e2e_p99_s"] > \
        doc["groups"]["fast"]["e2e_p99_s"]
    # fractions are fractions
    for frac in doc["phase_time_frac"].values():
        assert 0.0 <= frac <= 1.0


def test_slo_attribution_groups_by_replica_when_tagged():
    """ISSUE 14: records carrying a ``replica`` tag (a multi-replica
    router run) get a per-replica rollup next to the per-group one —
    per-replica tail attribution out of the same machinery — while
    untagged (single-engine) streams stay byte-identical."""
    events = [_tl_event(i, replica=i % 2, dc=0.5 + 0.05 * i)
              for i in range(8)]
    events.append(_tl_event(8, replica=1, q=9.0, ttft_s=9.4))
    doc = slo_attribution(collect_timelines(events), pct=0.95)
    assert set(doc["replicas"]) == {"0", "1"}
    assert doc["replicas"]["0"]["requests"] == 4
    assert doc["replicas"]["1"]["requests"] == 5
    # the tail (the queue-bound request) sits on replica 1, and its
    # tail row names the replica
    assert doc["replicas"]["1"]["tail_count"] == 1
    assert doc["replicas"]["0"]["tail_count"] == 0
    assert doc["replicas"]["1"]["e2e_p99_s"] > \
        doc["replicas"]["0"]["e2e_p99_s"]
    assert doc["tail"]["requests"][0]["replica"] == 1
    # untagged records: no replicas section at all
    plain = slo_attribution(collect_timelines(
        [_tl_event(i) for i in range(4)]), pct=0.95)
    assert "replicas" not in plain
    # the text rendering names replicas
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        render_slo_text,
    )

    text = render_slo_text(doc)
    assert "replica 0:" in text and "replica 1:" in text


def test_slo_attribution_rolls_up_priority_classes():
    """ISSUE 20: records carrying a ``priority`` tag (a ``policy=slo``
    run with priority classes) get a per-class rollup — attainment and
    deadline misses per class out of the same machinery. Emitters
    stamp ``priority`` absent-when-default, so untagged records in a
    tagged stream count as class 0; a wholly untagged (fifo) stream
    stays byte-identical with no priorities section at all."""
    events = [_tl_event(i, dc=0.5 + 0.05 * i, slo_met=True)
              for i in range(6)]                       # class 0, met
    events += [_tl_event(6 + i, priority=1, q=4.0 + i, ttft_s=4.2 + i,
                         slo_met=False, deadline_miss=True)
               for i in range(2)]                      # class 1, missed
    doc = slo_attribution(collect_timelines(events), pct=0.95)
    assert set(doc["priorities"]) == {"0", "1"}
    assert doc["priorities"]["0"]["requests"] == 6
    assert doc["priorities"]["1"]["requests"] == 2
    assert doc["priorities"]["0"]["slo_attainment"] == 1.0
    assert doc["priorities"]["1"]["slo_attainment"] == 0.0
    assert doc["priorities"]["1"]["deadline_misses"] == 2
    assert "deadline_misses" not in doc["priorities"]["0"]
    assert doc["priorities"]["1"]["e2e_p99_s"] > \
        doc["priorities"]["0"]["e2e_p99_s"]
    # a bool priority is not a class tag (schema types it int)
    plain = slo_attribution(collect_timelines(
        [_tl_event(i, priority=False) for i in range(4)]), pct=0.95)
    assert "priorities" not in plain
    # the text rendering names classes, attainment and misses
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        render_slo_text,
    )

    text = render_slo_text(doc)
    assert "priority 0:" in text and "priority 1:" in text
    assert "attainment 0.00%" in text
    assert "2 deadline miss(es)" in text


def test_gantt_and_chrome_trace_render():
    recs = collect_timelines([_tl_event(0), _tl_event(1, pe=0.4)])
    text = gantt_text(recs, width=32)
    assert "r0" in text and "r1" in text
    assert "Q" in text and "D" in text and "X" in text
    doc = chrome_trace(recs)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"queue", "prefill", "decode", "preempted"} <= names
    assert all(e["ph"] == "X" for e in doc["traceEvents"])


# -- the tier-1 engine gate ---------------------------------------------------

@pytest.fixture(scope="module")
def tiny_gpt2():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0,
                     dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


def _run_engine(model, params, tmp, *, timeline, n_req=5,
                overlap=None):
    """A forced-preemption serve run (tight pool) with per-tenant
    groups; returns (engine, events)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    obs.reset(out_dir=str(tmp), enabled=True)
    try:
        rng = np.random.RandomState(1)
        eng = ServeEngine(model, params, num_slots=4, block_size=4,
                          num_blocks=10, prefill_chunk=8,
                          max_model_len=32, timeline=timeline,
                          overlap=overlap)
        for i in range(n_req):
            eng.submit(rng.randint(1, 120, (9,)).astype(np.int32), 18,
                       group=f"tenant{i % 2}")
        eng.run()
        obs.flush()
    finally:
        obs.reset()
    events = [e for _, e, err in obs.iter_events(
        str(tmp / "events.jsonl")) if err is None]
    return eng, events


def test_engine_timeline_decomposition_sums_on_real_run(tiny_gpt2,
                                                        tmp_path):
    """The ISSUE 10 acceptance gate: on a real engine run under forced
    preemption, every finished request's emitted decomposition sums to
    its e2e within tolerance, the segment lists agree with the
    aggregates, the iteration ledger covers every iteration, and the
    whole stream passes the schema validator.

    ISSUE 12 extension (gate extended, not weakened): the run is a
    real OVERLAPPED forced-preemption run — the dispatch-ahead loop
    explicitly pinned on — so the decomposition must stay checkable
    with host work attributed concurrently with device time, and the
    mandatory pipeline drains (preemption acts on committed state
    only) must have latched."""
    _cfg, model, params = tiny_gpt2
    eng, events = _run_engine(model, params, tmp_path / "t",
                              timeline=True, overlap=True)
    assert eng.overlap                          # dispatch-ahead ran
    assert eng.overlap_flushes > 0              # preemption drained it
    assert eng.sched.n_preemptions > 0          # the run forced it
    recs = collect_timelines(events)
    assert sorted(r["request"] for r in recs) == \
        sorted(eng.finished.keys())
    for rec in recs:
        assert check_decomposition(rec) == [], rec["request"]
        assert rec["at"] == "finish"
        assert rec["e2e_s"] > 0 and rec["decode_s"] > 0
    # a preempted request's interval landed in the preempted phase and
    # its partial timeline was emitted at the preemption itself
    preempted = [r for r in recs if r["preemptions"] > 0]
    assert preempted and all(r["preempted_s"] > 0 for r in preempted)
    partials = [e for e in events if e.get("event") == "request_timeline"
                and e.get("at") == "preempt"]
    assert len(partials) == eng.sched.n_preemptions
    # admission-block attribution: with 5 requests over 4 tight slots
    # somebody waited at the head of the queue and says why
    blocked = [s for r in recs for s in r["segments"]
               if s.get("blocked_iters")]
    assert blocked and all(s["blocked_reason"] in
                           ("kv_capacity", "no_free_slot")
                           for s in blocked)
    # the per-iteration ledger: one event per engine iteration, token
    # accounting closed (ledger tokens sum to everything generated)
    ledgers = [e for e in events if e.get("event") == "iteration_ledger"]
    assert len(ledgers) == eng.iterations
    assert [e["iteration"] for e in ledgers] == list(range(
        eng.iterations))
    assert sum(e["tokens"] for e in ledgers) == eng.tokens_generated
    assert all(0.0 <= e["kv_used_frac"] <= 1.0 for e in ledgers)
    assert all(e["dur_s"] >= e["prefill_s"] + e["decode_s"] - 1e-5
               for e in ledgers)
    # the SLO summary aggregates close over the same accounting
    slo = eng.slo_summary()
    fracs = [slo[f"{ph}_time_frac"] for ph in
             ("queue", "prefill", "decode", "preempted", "overhead")]
    assert sum(fracs) == pytest.approx(1.0, abs=0.01)
    assert slo["preempted_time_frac"] > 0
    assert slo["queue_wait_p99_s"] >= slo["queue_wait_p50_s"] >= 0
    # the produced stream passes the schema validator end to end
    count, errors = obs.validate_events_file(
        str(tmp_path / "t" / "events.jsonl"))
    assert not errors and count > 0


def test_engine_timeline_off_restores_pre_tracing_stream(tiny_gpt2,
                                                         tmp_path):
    """HSTD_SERVE_TIMELINE=off must be byte-identical to the pre-PR
    telemetry: no new event subtypes, no new fields on existing serve
    events, no new keys in the SLO report."""
    _cfg, model, params = tiny_gpt2
    eng, events = _run_engine(model, params, tmp_path / "t",
                              timeline=False, n_req=3)
    serve_ev = [e for e in events if e["type"] == "serve"]
    kinds = {e["event"] for e in serve_ev}
    assert kinds <= {"submit", "admit", "first_token", "finish",
                     "preempt", "bucket_switch", "report"}
    new_keys = {"at", "e2e_s", "queue_s", "prefill_s", "decode_s",
                "preempted_s", "overhead_s", "segments", "group",
                "blocked_iters", "blocked_reason", "iteration",
                "dur_s", "decode_slots", "waiting", "kv_used_frac",
                "queue_wait_p50_s", "queue_wait_p99_s",
                "queue_time_frac", "prefill_time_frac",
                "decode_time_frac", "preempted_time_frac",
                "overhead_time_frac"}
    for e in serve_ev:
        leaked = new_keys & set(e)
        assert not leaked, (e["event"], leaked)
    assert not any(k in eng.slo_summary() for k in new_keys)
    # and the accounting stayed inert host-side too
    assert all(v == 0.0 for r in eng.finished.values()
               for v in r.phase_s.values())
    assert all(not r.segments for r in eng.finished.values())


def test_engine_overlap_off_restores_pre_overlap_telemetry(tiny_gpt2,
                                                           tmp_path):
    """ISSUE 12: ``HSTD_SERVE_OVERLAP=off`` must be byte-identical to
    the pre-PR (serial-loop) telemetry — allowlist-gated: no new
    event subtypes, no overlap keys on any serve event, nothing new
    in the SLO report, and the full PR-10 timeline machinery intact
    (same forced-preemption run, same decomposition gate)."""
    _cfg, model, params = tiny_gpt2
    eng, events = _run_engine(model, params, tmp_path / "t",
                              timeline=True, overlap=False)
    assert not eng.overlap and eng.overlap_flushes == 0
    assert eng.sched.n_preemptions > 0
    serve_ev = [e for e in events if e["type"] == "serve"]
    kinds = {e["event"] for e in serve_ev}
    assert kinds <= {"submit", "admit", "first_token", "finish",
                     "preempt", "bucket_switch", "report",
                     "request_timeline", "iteration_ledger"}
    for e in serve_ev:
        leaked = {"overlap", "overlap_flushes"} & set(e)
        assert not leaked, (e["event"], leaked)
    slo = eng.slo_summary()
    assert "overlap" not in slo and "overlap_flushes" not in slo
    # the serial stream still passes the full decomposition gate
    for rec in collect_timelines(events):
        assert check_decomposition(rec) == [], rec["request"]


# -- obsctl timeline|slo|tail CLI ---------------------------------------------

@pytest.fixture()
def synthetic_dirs(tmp_path):
    """Two per-host dirs of schema-valid timeline events (one tail
    request dominated by queue, one preempted request)."""
    a = [_tl_event(0, group="t0"), _tl_event(2, pe=0.4, group="t0"),
         _ledger_event(0), _ledger_event(1, t=1001.0)]
    b = [_tl_event(1, group="t1"), _tl_event(3, q=6.0, group="t1")]
    _write_events(str(tmp_path / "h0" / "events.jsonl"), a)
    _write_events(str(tmp_path / "h1" / "events.jsonl"), b)
    return [str(tmp_path / "h0"), str(tmp_path / "h1")]


def _run_obsctl(*argv):
    return subprocess.run([sys.executable, _OBSCTL, *argv],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True, cwd=_REPO)


def test_cli_timeline_gantt_trace_and_determinism(synthetic_dirs,
                                                  tmp_path):
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (
        validate_trace_file,
    )

    trace = str(tmp_path / "chrome.json")
    proc = _run_obsctl("timeline", *synthetic_dirs, "--trace", trace)
    assert proc.returncode == 0, proc.stderr
    assert "r0" in proc.stdout and "r3" in proc.stdout
    n, errors = validate_trace_file(trace)
    assert n > 0 and not errors
    # byte-identical across input orderings (trace file too)
    rev = _run_obsctl("timeline", *reversed(synthetic_dirs),
                      "--trace", str(tmp_path / "chrome2.json"))
    assert rev.returncode == 0 and rev.stdout == proc.stdout
    assert (tmp_path / "chrome.json").read_bytes() == \
        (tmp_path / "chrome2.json").read_bytes()
    js = _run_obsctl("timeline", "--json", *synthetic_dirs)
    recs = json.loads(js.stdout)
    assert [r["request"] for r in recs] == [0, 1, 2, 3]


def test_cli_slo_attribution_and_determinism(synthetic_dirs):
    proc = _run_obsctl("slo", *synthetic_dirs, "--percentile", "90")
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["tail"]["dominant_phase_counts"] == {"queue": 1}
    assert set(doc["groups"]) == {"t0", "t1"}
    rev = _run_obsctl("slo", *reversed(synthetic_dirs),
                      "--percentile", "90")
    assert rev.stdout == proc.stdout
    text = _run_obsctl("slo", "--text", *synthetic_dirs)
    assert text.returncode == 0 and "dominated by queue" in text.stdout


def test_cli_timeline_and_slo_reject_malformed_input(tmp_path):
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "events.jsonl").write_text(
        '{"torn json\n'
        + json.dumps(_tl_event(0)) + "\n")
    for cmd in ("timeline", "slo"):
        proc = _run_obsctl(cmd, str(bad))
        assert proc.returncode == 1
        assert "unparseable" in proc.stderr
    # mistyped field -> schema validation failure, not silent garbage
    drift = tmp_path / "drift"
    drift.mkdir()
    ev = _tl_event(0)
    ev["queue_s"] = "fast"
    _write_events(str(drift / "events.jsonl"), [ev])
    proc = _run_obsctl("timeline", str(drift))
    assert proc.returncode == 1 and "queue_s" in proc.stderr
    # internally inconsistent decomposition -> rejected too
    sick = tmp_path / "sick"
    sick.mkdir()
    ev = _tl_event(0)
    ev["decode_s"] = 40.0
    _write_events(str(sick / "events.jsonl"), [ev])
    proc = _run_obsctl("timeline", str(sick))
    assert proc.returncode == 1 and "inconsistent" in proc.stderr
    # empty input
    empty = tmp_path / "empty"
    empty.mkdir()
    assert _run_obsctl("timeline", str(empty)).returncode == 1
    assert _run_obsctl("tail", str(empty / "nope.jsonl")).returncode == 1
    # bad knob values: clean diagnostic + exit 1, not a traceback
    good = tmp_path / "good"
    good.mkdir()
    _write_events(str(good / "events.jsonl"), [_tl_event(0)])
    proc = _run_obsctl("timeline", str(good), "--width", "0")
    assert proc.returncode == 1 and "--width" in proc.stderr
    proc = _run_obsctl("slo", str(good), "--percentile", "0")
    assert proc.returncode == 1 and "--percentile" in proc.stderr
    seeded = str(good / "events.jsonl")
    proc = _run_obsctl("tail", seeded, "--window", "0", "--updates", "1")
    assert proc.returncode == 1 and "--window" in proc.stderr


def test_cli_tail_follows_live_appends(tmp_path):
    """The live-follow contract end to end: the subprocess prints one
    rolling-gauge line per poll that saw new events and picks up lines
    appended AFTER it started."""
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [_ledger_event(0, waiting=4)])
    proc = subprocess.Popen(
        [sys.executable, _OBSCTL, "tail", path, "--updates", "2",
         "--interval", "0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO)
    try:
        # BLOCK on the first update line (no startup race): line 1 was
        # pre-seeded, so its gauge line proves the first poll landed
        first = proc.stdout.readline()
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(_ledger_event(1, t=1001.0, waiting=7))
                    + "\n")
        out, err = proc.communicate(timeout=30)
    finally:
        proc.kill()
    assert proc.returncode == 0, err
    lines = [ln for ln in (first + out).splitlines() if ln.strip()]
    assert len(lines) == 2
    assert "waiting=4" in lines[0]
    assert "waiting=7" in lines[1] and "iter=1" in lines[1]


def test_cli_tail_exits_nonzero_on_malformed_stream(tmp_path):
    path = str(tmp_path / "events.jsonl")
    _write_events(path, [_ledger_event(0)])
    with open(path, "a", encoding="utf-8") as f:
        f.write("not json at all\n")
    proc = _run_obsctl("tail", path, "--updates", "5", "--interval",
                       "0.05")
    assert proc.returncode == 1
    assert "unparseable" in proc.stderr


# -- the no-jax import contract, extended (ISSUE 10 satellite) ----------------

def test_obs_timeline_runs_without_jax():
    """obs/timeline.py and every obsctl subcommand stay on the
    stdlib-only side of the obs contract — asserted statically via
    graftlint R1's import-time reachability (ISSUE 15): complete over
    all import edges, not just the subcommand paths a poison run
    happened to execute. The slow-tier subprocess smokes
    (test_obsctl / test_telemetry_schema) backstop the static view at
    runtime."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
        PACKAGE,
        load_project,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.rules import (
        check_r1,
        r1_reachability,
        r1_zone_roots,
    )

    project = load_project(_REPO)
    assert check_r1(project) == []
    # timeline is a zone ROOT (all of obs/ is), so even its
    # lazily-imported consumers can't smuggle jax in at import time
    assert f"{PACKAGE}/obs/timeline.py" in r1_zone_roots(project)
    # the fleet-trace stitcher (ISSUE 19) rides the same contract —
    # `obsctl trace|fleet` run on the same jax-less boxes
    assert f"{PACKAGE}/obs/trace.py" in r1_zone_roots(project)
    assert "scripts/obsctl.py" in r1_reachability(project)
