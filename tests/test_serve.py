"""serve/ subsystem: block-manager accounting, iteration-level
scheduler policy, and the engine exactness gate — continuous-batched
greedy decode must be token-for-token identical to per-request
``generate_causal`` (with and without preemption), for both the GPT-2
and Llama/GQA cache layouts."""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    BlockManager,
    PoolExhausted,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    DECODE,
    PREFILL,
    WAITING,
    Request,
    Scheduler,
)


# -- block manager (pure host) -----------------------------------------------

def test_block_alloc_free_roundtrip():
    bm = BlockManager(num_blocks=9, block_size=4)
    assert bm.num_free == 8                        # block 0 reserved
    got = bm.allocate(3)
    assert len(got) == 3 and 0 not in got
    assert bm.num_used == 3 and bm.peak_used == 3
    bm.free(got)
    assert bm.num_free == 8 and bm.peak_used == 3  # peak latches
    with pytest.raises(ValueError):
        bm.free([got[0], got[0]])                  # double free
    with pytest.raises(ValueError):
        bm.free([0])                               # the null block


def test_pool_exhausted_is_all_or_nothing():
    bm = BlockManager(num_blocks=5, block_size=4)
    bm.allocate(2)
    with pytest.raises(PoolExhausted):
        bm.allocate(3)
    assert bm.num_free == 2                        # nothing leaked


def test_grow_and_trim_follow_context():
    bm = BlockManager(num_blocks=9, block_size=4)
    table = []
    assert len(bm.grow(table, 1)) == 1             # 1 token -> 1 block
    assert bm.grow(table, 4) == []                 # still fits
    assert len(bm.grow(table, 5)) == 1             # crosses the boundary
    assert len(table) == 2
    bm.trim(table, 3)                              # back to 1 block
    assert len(table) == 1 and bm.num_free == 7


def test_fragmentation_is_last_block_padding():
    bm = BlockManager(num_blocks=9, block_size=4)
    # contexts 5 and 8: held slots 8 + 8, used 13 -> 3/16 wasted
    assert bm.fragmentation([5, 8]) == pytest.approx(3 / 16)
    assert bm.fragmentation([]) == 0.0


# -- scheduler (pure host) ---------------------------------------------------

def _sched(num_slots=2, num_blocks=9, block_size=4, chunk=4, max_len=32):
    return Scheduler(num_slots, BlockManager(num_blocks, block_size),
                     chunk, max_len)


def test_admission_is_fifo_into_free_slots():
    s = _sched()
    reqs = [Request(prompt=np.arange(1, 4), max_new_tokens=4)
            for _ in range(3)]
    for r in reqs:
        s.submit(r)
    admitted = s.admit()
    assert [sl.request.rid for sl in admitted] == [reqs[0].rid, reqs[1].rid]
    assert reqs[0].state == PREFILL and reqs[2].state == WAITING
    # padded-prompt reservation: 3 tokens pad to chunk 4 -> 1 block each
    assert s.blocks.num_used == 2
    assert s.admit() == []                         # no free slot


def test_admission_respects_pool_capacity():
    s = _sched(num_slots=2, num_blocks=4)          # 3 allocatable blocks
    a = Request(prompt=np.arange(1, 9), max_new_tokens=4)   # pad 8 -> 2 blocks
    b = Request(prompt=np.arange(1, 9), max_new_tokens=4)
    s.submit(a)
    s.submit(b)
    assert [sl.request.rid for sl in s.admit()] == [a.rid]
    assert b.state == WAITING                      # FIFO: b never jumps


def test_submit_rejects_over_length_requests():
    s = _sched(max_len=16)
    with pytest.raises(ValueError):
        s.submit(Request(prompt=np.arange(1, 14), max_new_tokens=8))


def test_submit_rejects_requests_that_can_never_fit_the_pool():
    """A request whose worst-case block need exceeds the WHOLE pool
    would otherwise livelock the engine: admit() parks it at the queue
    head forever (or a lone decode slot preempts itself in a loop)."""
    s = _sched(num_slots=1, num_blocks=4, block_size=4, max_len=32)
    with pytest.raises(ValueError, match="KV blocks"):
        s.submit(Request(prompt=np.arange(1, 9), max_new_tokens=12))
    # exactly at capacity is fine (3 blocks hold 12 tokens lifetime)
    s.submit(Request(prompt=np.arange(1, 9), max_new_tokens=4))


def test_scheduler_rejects_chunk_not_dividing_max_model_len():
    """padded_prompt_len must never exceed max_model_len (block tables
    are sized for it) — enforced by requiring the chunk to divide it."""
    with pytest.raises(ValueError, match="prefill_chunk"):
        _sched(chunk=48, max_len=64)


def test_preemption_evicts_youngest_and_requeues_front():
    s = _sched(num_slots=2, num_blocks=6, block_size=4, chunk=4)
    old = Request(prompt=np.arange(1, 5), max_new_tokens=16)
    young = Request(prompt=np.arange(1, 5), max_new_tokens=16)
    s.submit(old)
    s.submit(young)
    s.admit()
    for slot in s.slots:                            # fake finished prefill
        s.finish_prefill(slot)
        slot.request.output = [7, 8]
        slot.context_len = 6
    # 4 allocatable blocks, both slots at 2 blocks each once they cross
    # context 8; growing both is impossible -> youngest goes
    s.slots[0].context_len = s.slots[1].context_len = 8
    preempted = s.ensure_decode_capacity()
    assert [r.rid for r in preempted] == [young.rid]
    assert young.state == WAITING and s.waiting[0] is young
    # recompute style: generated tokens folded into the prompt
    assert list(young.prompt) == [1, 2, 3, 4, 7, 8]
    assert young.output == [] and young.preemptions == 1
    assert old.state == DECODE                     # survivor kept its slot


# -- paged addressing primitives (ops/attention.py) --------------------------

def test_paged_attention_matches_contiguous():
    """gather/scatter round-trip + paged_attention == xla_attention over
    the same contiguous KV — the addressing contract the engine's
    cache-assembly path is built on."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        gather_paged_kv,
        paged_attention,
        scatter_paged_kv,
        xla_attention,
    )

    rng = np.random.RandomState(0)
    S, H, D, bs, nb_per = 3, 2, 4, 4, 3          # max_ctx = 12
    max_ctx = bs * nb_per
    ctx = np.array([5, 12, 1], np.int32)
    k_ref = rng.randn(S, H, max_ctx, D).astype(np.float32)
    v_ref = rng.randn(S, H, max_ctx, D).astype(np.float32)
    # scatter each slot's context token-by-token into a shared pool
    # through shuffled per-slot block tables (block 0 reserved null)
    pool_k = jnp.zeros((1 + S * nb_per, bs, H, D), jnp.float32)
    pool_v = jnp.zeros_like(pool_k)
    ids = rng.permutation(np.arange(1, 1 + S * nb_per))
    tables = ids.reshape(S, nb_per).astype(np.int32)
    for s in range(S):
        for p in range(int(ctx[s])):
            row = jnp.asarray(tables[s:s + 1])
            pos = jnp.asarray([p], jnp.int32)
            pool_k = scatter_paged_kv(pool_k, row, pos,
                                      jnp.asarray(k_ref[s:s + 1, :, p]))
            pool_v = scatter_paged_kv(pool_v, row, pos,
                                      jnp.asarray(v_ref[s:s + 1, :, p]))
    gk = np.asarray(gather_paged_kv(pool_k, jnp.asarray(tables)))
    for s in range(S):
        np.testing.assert_array_equal(gk[s, :, :ctx[s]], k_ref[s, :, :ctx[s]])
    q = jnp.asarray(rng.randn(S, H, D).astype(np.float32))
    got = paged_attention(q, pool_k, pool_v, jnp.asarray(tables),
                          jnp.asarray(ctx))
    valid = np.arange(max_ctx)[None, :] < ctx[:, None]
    mask = jnp.asarray(np.where(valid, 0.0, -1e9)[:, None, None, :],
                       jnp.float32)
    want = xla_attention(q[:, :, None, :], jnp.asarray(k_ref * valid[:, None, :, None]),
                         jnp.asarray(v_ref * valid[:, None, :, None]),
                         mask=mask)[:, :, 0, :]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# -- engine exactness (the gate) ---------------------------------------------

@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


def _reference(model, params, prompt, max_new, eos):
    """Per-request generate_causal greedy, trimmed EOS-inclusive."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
    )

    ref = list(np.asarray(generate_causal(
        model, params, jnp.asarray(prompt)[None], max_new_tokens=max_new))[0])
    if eos in ref:
        ref = ref[:ref.index(eos) + 1]
    return [int(t) for t in ref]


def _assert_engine_exact(model, params, trace, eos, ref_model=None,
                         **engine_kw):
    """``ref_model`` overrides the generate_causal oracle — an int8
    engine's contract is generate_causal on the int8-cache config (int8
    vs fp tokens legitimately differ; quantization is deterministic)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    ref_model = ref_model if ref_model is not None else model
    eng = ServeEngine(model, params, **engine_kw)
    reqs = [eng.submit(p, m) for p, m in trace]
    eng.run()
    for (prompt, max_new), req in zip(trace, reqs):
        got = [int(t) for t in eng.output_ids(req)]
        assert got == _reference(ref_model, params, prompt, max_new,
                                 eos), \
            f"request {req.rid} diverged (preemptions={req.preemptions})"
    return eng


def test_engine_matches_generate_causal_mixed_lengths(gpt2_setup):
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(0)
    # few DISTINCT prompt lengths: every length is a fresh XLA program
    # on the reference side, and the gate is semantics, not compile time
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(5, 7), (9, 3), (12, 10), (5, 1), (9, 8)]]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=3, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=64)
    assert eng.stats().preemptions == 0
    assert eng.stats().tokens_generated == sum(
        len(eng.output_ids(r)) for r in eng.finished.values())


def test_engine_exact_under_preemption(gpt2_setup):
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(5)]
    # 9 allocatable blocks of 4 = 36 resident tokens for 5 requests
    # that each want 27: preemption is forced
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=4, block_size=4, num_blocks=10,
                               prefill_chunk=8, max_model_len=32)
    assert eng.stats().preemptions > 0


def test_engine_stops_at_eos_exactly(gpt2_setup):
    import dataclasses

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 120, (9,)).astype(np.int32)
    # pick the reference's 3rd greedy token as EOS so the engine must
    # stop early, then rebuild the model around that id
    ref = _reference(model, params, prompt, 12, eos=-1)
    eos_cfg = dataclasses.replace(cfg, eos_token_id=int(ref[2]))
    eos_model = type(model)(eos_cfg)
    _assert_engine_exact(eos_model, params, [(prompt, 12)],
                         eos_cfg.eos_token_id, num_slots=2, block_size=4,
                         num_blocks=20, prefill_chunk=8, max_model_len=64)


def test_engine_exact_llama_gqa():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128, eos_token_id=127,
                      pad_token_id=0, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg, seed=0)
    rng = np.random.RandomState(3)
    trace = [(rng.randint(3, 120, (p,)).astype(np.int32), m)
             for p, m in [(6, 6), (11, 9), (6, 4)]]
    _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                         num_slots=2, block_size=8, num_blocks=20,
                         prefill_chunk=8, max_model_len=64)


def test_engine_rejects_unsupported_configs(gpt2_setup):
    """The ISSUE 3 rejection surface after ISSUE 9: int8-KV and
    sliding-window configs are now SERVED (their engines construct and
    carry the right pool dtypes), and the rejections that remain are
    genuine unsupported shapes plus unparseable knob values."""
    import dataclasses

    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    int8 = type(model)(dataclasses.replace(cfg, kv_cache_dtype="int8"))
    eng = ServeEngine(int8, params, num_blocks=4, block_size=4,
                      max_model_len=16, prefill_chunk=8)
    assert eng.kv_cache_dtype == "int8"
    assert {str(p.dtype) for p in eng._pools} == {"int8", "float32"}
    # the knob form: an fp model rebuilt around int8 pool storage
    eng = ServeEngine(model, params, num_blocks=4, block_size=4,
                      max_model_len=16, prefill_chunk=8,
                      kv_cache_dtype="int8")
    assert eng.model.config.kv_cache_dtype == "int8"
    with pytest.raises(ValueError, match="max_position_embeddings"):
        ServeEngine(model, params, num_blocks=4, max_model_len=1024)
    with pytest.raises(ValueError, match="HSTD_SERVE_KERNEL"):
        ServeEngine(model, params, num_blocks=4, block_size=4,
                    max_model_len=16, prefill_chunk=8, kernel="cuda")
    with pytest.raises(ValueError, match="HSTD_SERVE_KV_DTYPE"):
        ServeEngine(model, params, num_blocks=4, block_size=4,
                    max_model_len=16, prefill_chunk=8,
                    kv_cache_dtype="fp8")


# -- telemetry ---------------------------------------------------------------

def test_engine_emits_valid_serve_events(gpt2_setup, tmp_path):
    cfg, model, params = gpt2_setup
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        rng = np.random.RandomState(4)
        trace = [(rng.randint(1, 120, (5,)).astype(np.int32), 7)
                 for _ in range(3)]
        _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                             num_slots=2, block_size=4, num_blocks=20,
                             prefill_chunk=8, max_model_len=64)
        obs.flush()
    finally:
        obs.reset()
    events = [e for _, e, err in obs.iter_events(str(out / "events.jsonl"))
              if err is None]
    serve_ev = [e for e in events if e["type"] == "serve"]
    kinds = {e["event"] for e in serve_ev}
    assert {"submit", "admit", "first_token", "finish"} <= kinds
    finishes = [e for e in serve_ev if e["event"] == "finish"]
    assert len(finishes) == 3 and all("request" in e for e in finishes)
    ttfts = [e for e in serve_ev if e["event"] == "first_token"]
    assert all(e.get("ttft_s", 0) > 0 for e in ttfts)
    count, errors = obs.validate_events_file(str(out / "events.jsonl"))
    assert not errors and count >= len(events)


def test_generate_causal_decode_phase_split_telemetry(gpt2_setup, tmp_path):
    """ROADMAP "Decode-phase split": the one-shot path now reports TTFT
    and decode tokens/sec as separate series, with prefill and decode
    visible as separate spans."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        generate_causal,
    )

    cfg, model, params = gpt2_setup
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        prompt = np.random.RandomState(5).randint(1, 120, (1, 6))
        generate_causal(model, params, jnp.asarray(prompt),
                        max_new_tokens=4)
        obs.flush()
    finally:
        obs.reset()
    events = [e for _, e, err in obs.iter_events(str(out / "events.jsonl"))
              if err is None]
    metrics = {e["name"] for e in events if e["type"] == "metric"}
    assert "generate/causal_ttft_s" in metrics
    assert "generate/causal_decode_tokens_per_sec" in metrics
    spans = {e["name"] for e in events if e["type"] == "span"}
    assert {"generate/causal_prefill", "generate/causal_decode"} <= spans

# -- ISSUE 5 decode fast path: bucketed gather, batched prefill, sampling ----

def test_parse_gather_buckets_ladder():
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        parse_gather_buckets,
    )

    # auto: quarter width + full width, block-rounded
    assert parse_gather_buckets(None, 512, 16) == [128, 512]
    assert parse_gather_buckets("auto", 64, 8) == [16, 64]
    # explicit env form: rounded UP to block multiples, clipped, full
    # width always present, dedup + sorted
    assert parse_gather_buckets("60,200,9999", 512, 16) == [64, 208, 512]
    # "full" disables bucketing
    assert parse_gather_buckets("full", 512, 16) == [512]
    # sequences work too (engine kwarg form)
    assert parse_gather_buckets([64, 512], 512, 16) == [64, 512]
    with pytest.raises(ValueError, match="unparseable"):
        parse_gather_buckets("wide", 512, 16)


def test_gather_bucket_width_matches_full_width_at_boundaries():
    """ops-level bucket contract: for contexts at bucket-1 / bucket /
    bucket+1, the width-restricted gather returns exactly the first
    `width` logical positions of the full-width gather."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        gather_paged_kv,
    )

    rng = np.random.RandomState(7)
    bs, nb_per, S, H, D = 4, 6, 2, 2, 3          # span 24, bucket 8
    pool = jnp.asarray(rng.randn(1 + S * nb_per, bs, H, D)
                       .astype(np.float32))
    tables = jnp.asarray(
        rng.permutation(np.arange(1, 1 + S * nb_per))
        .reshape(S, nb_per).astype(np.int32))
    full = np.asarray(gather_paged_kv(pool, tables))
    for width in (8, 16):
        got = np.asarray(gather_paged_kv(pool, tables, width=width))
        np.testing.assert_array_equal(got, full[:, :, :width])
    with pytest.raises(ValueError, match="multiple"):
        gather_paged_kv(pool, tables, width=10)
    with pytest.raises(ValueError, match="block table holds"):
        gather_paged_kv(pool, tables, width=32)


def test_engine_exact_across_bucket_boundaries(gpt2_setup):
    """The tentpole exactness gate at every bucket boundary: resident
    contexts hit bucket-1, bucket, and bucket+1 (prompt lengths 15/16/17
    against a 16-wide first bucket, decode crossing it mid-request), and
    the greedy stream must stay token-for-token generate_causal."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(6)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), 6)
             for p in (15, 16, 17)]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=3, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=64,
                               gather_buckets=[16, 32])
    assert eng.gather_buckets == [16, 32, 64]
    # decode really ran below full width (the fast path engaged) and
    # crossing the boundary forced at least one bucket switch
    assert eng.bucket_switches >= 1
    assert eng.stats().gather_waste_mean < 1.0


def test_batched_prefill_isolation_and_batching(gpt2_setup):
    """Batched prefill packs concurrent prompts into one dispatch
    (fewer dispatches than chunks) without cross-request leakage: every
    request's stream equals its solo generate_causal reference, and a
    request served alongside others equals the same request served
    ALONE."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(8)
    prompts = [rng.randint(1, 120, (11,)).astype(np.int32)
               for _ in range(4)]
    trace = [(p, 5) for p in prompts]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=4, block_size=4, num_blocks=60,
                               prefill_chunk=8, max_model_len=64)
    # 4 requests x 2 chunks each admitted together: batching must pack
    # them (strictly fewer dispatches than chunks)
    assert eng.prefill_dispatches < eng.prefill_chunks
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    solo = ServeEngine(model, params, num_slots=4, block_size=4,
                       num_blocks=60, prefill_chunk=8, max_model_len=64)
    req = solo.submit(prompts[0], 5)
    solo.run()
    batched_req = next(r for r in eng.finished.values()
                       if list(r.prompt[:11]) == list(prompts[0]))
    assert list(solo.output_ids(req)) == list(eng.output_ids(batched_req))


def test_sampled_serve_is_seed_deterministic_across_preemption(gpt2_setup):
    """The seeded-determinism gate for sampled mode: identical seeds
    reproduce bitwise-identical streams, preemption/requeue does not
    change them, a different seed changes only its own stream, and
    greedy requests in the same batch stay exactly generate_causal."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(9)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(4)]
    kws = [dict(temperature=0.9, top_k=20, top_p=0.9, seed=s)
           for s in (1, 2, 3)] + [dict()]        # request 3 stays greedy

    def run(num_blocks, kws):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
            ServeEngine,
        )

        eng = ServeEngine(model, params, num_slots=3, block_size=4,
                          num_blocks=num_blocks, prefill_chunk=8,
                          max_model_len=32)
        reqs = [eng.submit(p, m, **kw) for (p, m), kw in zip(trace, kws)]
        eng.run()
        return [[int(t) for t in eng.output_ids(r)] for r in reqs], eng

    base, eng = run(40, kws)
    again, _ = run(40, kws)
    assert again == base                        # bitwise reproducible
    tight, teng = run(9, kws)                   # tight pool: preemption
    assert teng.stats().preemptions > 0
    assert tight == base                        # preemption-invariant
    reseeded, _ = run(40, [dict(kws[0], seed=99)] + kws[1:])
    assert reseeded[0] != base[0]               # the seed matters
    assert reseeded[1:] == base[1:]             # ...only for its stream
    # the greedy rider is untouched by its sampled batchmates
    p, m = trace[3]
    assert base[3] == _reference(model, params, p, m, cfg.eos_token_id)


def test_request_rejects_bad_sampling_params():
    with pytest.raises(ValueError, match="temperature"):
        Request(prompt=np.arange(1, 4), max_new_tokens=2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_p"):
        Request(prompt=np.arange(1, 4), max_new_tokens=2, top_p=1.5)
    with pytest.raises(ValueError, match="top_k"):
        Request(prompt=np.arange(1, 4), max_new_tokens=2, top_k=-2)


# -- ISSUE 6: speculative decoding inside the engine -------------------------

@pytest.fixture(scope="module")
def spec_draft():
    """An INDEPENDENTLY-initialized 1-layer draft over the gpt2_setup
    vocabulary: disagrees with the target often enough that rejection /
    rewind paths are genuinely exercised (a self-draft of a tiny
    random-init model is near-perfect — upper blocks are ~identity)."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=1,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return model, init_params(model, cfg, seed=5)


def test_speculative_engine_exact_across_bucket_boundaries(gpt2_setup,
                                                           spec_draft):
    """The tentpole exactness gate, speculative edition: greedy
    draft-k/verify serving stays token-for-token generate_causal with
    resident contexts crossing every bucket boundary (prompts 15/16/17
    against a 16-wide first bucket) and an adversarial draft forcing
    real rejections (acceptance < 1) — the context-rewind path is load-
    bearing, not idle."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(6)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), 6)
             for p in (15, 16, 17)]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=3, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=64,
                               gather_buckets=[16, 32],
                               speculate_k=2, draft=spec_draft)
    assert eng.gather_buckets == [16, 32, 64]
    stats = eng.stats()
    assert stats.draft_proposed > 0
    assert 0 <= stats.acceptance_rate < 1     # rejections actually hit
    assert stats.spec_windows > 0
    assert 0 < stats.verify_waste_mean < 1    # rejected tails accounted
    # no block leaked through the window-reserve/commit/trim cycle
    # (prefix caching keeps finished prompts' blocks CACHED, not free —
    # conservation counts both)
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)
    assert eng.blocks.num_used == 0


def test_speculative_engine_exact_under_preemption_rewind_leak_free(
        gpt2_setup, spec_draft):
    """Forced recompute preemption + rejection storms: outputs stay
    exact, and every block comes back to the free list (no lost /
    double-freed blocks across grow-for-window -> reject -> trim ->
    preempt cycles)."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(5)]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=4, block_size=4, num_blocks=11,
                               prefill_chunk=8, max_model_len=32,
                               speculate_k=2, draft=spec_draft)
    assert eng.stats().preemptions > 0
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)
    assert eng.blocks.num_used == 0


def test_sampled_speculative_serve_seed_deterministic_across_preemption(
        gpt2_setup, spec_draft):
    """Extends the ISSUE 5 seeded-determinism gate to speculative mode:
    the whole verify window's randomness derives from (request seed,
    window-start token index), so sampled speculative streams are
    bitwise seed-reproducible INCLUDING across recompute preemption
    (windows re-start at the same committed index), reseeding changes
    only its own stream, and a greedy rider stays generate_causal."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(9)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(4)]
    kws = [dict(temperature=0.9, top_k=20, top_p=0.9, seed=s)
           for s in (1, 2, 3)] + [dict()]        # request 3 stays greedy

    def run(num_blocks, kws):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
            ServeEngine,
        )

        eng = ServeEngine(model, params, num_slots=3, block_size=4,
                          num_blocks=num_blocks, prefill_chunk=8,
                          max_model_len=32, speculate_k=2,
                          draft=spec_draft)
        reqs = [eng.submit(p, m, **kw) for (p, m), kw in zip(trace, kws)]
        eng.run()
        return [[int(t) for t in eng.output_ids(r)] for r in reqs], eng

    base, eng = run(40, kws)
    assert eng.stats().draft_proposed > 0
    again, _ = run(40, kws)
    assert again == base                        # bitwise reproducible
    tight, teng = run(11, kws)                  # tight pool: preemption
    assert teng.stats().preemptions > 0
    assert tight == base                        # preemption-invariant
    reseeded, _ = run(40, [dict(kws[0], seed=99)] + kws[1:])
    assert reseeded[0] != base[0]               # the seed matters
    assert reseeded[1:] == base[1:]             # ...only for its stream
    p, m = trace[3]
    assert base[3] == _reference(model, params, p, m, cfg.eos_token_id)


def test_speculative_engine_knobs_and_rejections(gpt2_setup, spec_draft,
                                                 monkeypatch):
    """Constructor/env contract: env-driven speculate_k, ladder pruning
    of sub-window buckets, window-aware submit rejection, bad-knob
    errors. Host-side only — nothing here dispatches."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_SPECULATE_K,
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    kw = dict(num_slots=2, block_size=4, num_blocks=20, prefill_chunk=8,
              max_model_len=32)
    monkeypatch.setenv(ENV_SPECULATE_K, "2")
    eng = ServeEngine(model, params, draft=spec_draft, **kw)
    assert eng.speculate_k == 2 and eng.speculative
    monkeypatch.delenv(ENV_SPECULATE_K)
    # the engine-level window reservation: prompt + max_new + k must
    # fit max_model_len (the verify window writes k past the last
    # committed position)
    with pytest.raises(ValueError, match="verify-window"):
        eng.submit(np.arange(1, 9), 24)       # 8 + 24 + 2 > 32
    eng.submit(np.arange(1, 9), 22)           # 8 + 22 + 2 == 32: fits
    # buckets narrower than the window can never be selected: pruned
    sp = ServeEngine(model, params, speculate_k=7, draft=spec_draft,
                     gather_buckets=[4, 16], **kw)
    assert sp.gather_buckets == [16, 32]
    with pytest.raises(ValueError, match="speculate_k"):
        ServeEngine(model, params, speculate_k=-1, **kw)
    with pytest.raises(ValueError, match="vocabulary"):
        import dataclasses

        other_cfg = dataclasses.replace(spec_draft[0].config,
                                        vocab_size=64)
        other = type(spec_draft[0])(other_cfg)
        ServeEngine(model, params, speculate_k=2,
                    draft=(other, spec_draft[1]), **kw)


def test_warmup_sampled_precompiles_sampled_variants(gpt2_setup, tmp_path):
    """The ROADMAP `warmup(sampled=True)` knob: after it, sampled
    traffic triggers ZERO mid-serve compiles (without it the sampled
    step variants compile lazily on the first sampled batch)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    obs.reset(out_dir=str(tmp_path / "telemetry"), enabled=True)
    try:
        eng = ServeEngine(model, params, num_slots=3, block_size=4,
                          num_blocks=40, prefill_chunk=8,
                          max_model_len=64)
        eng.warmup(sampled=True)
        tracker = obs.compile_tracker()
        count0 = tracker.count
        rng = np.random.RandomState(12)
        for s in range(3):
            eng.submit(rng.randint(1, 120, (9,)).astype(np.int32), 8,
                       temperature=0.8, top_k=10, seed=s)
        eng.run()
        assert tracker.count == count0, \
            "sampled serving recompiled after warmup(sampled=True)"
    finally:
        obs.reset()


def test_block_manager_gather_waste_accounting():
    """note_gather latches the PEAK bucket-padded read waste and keeps
    a token-weighted mean — the decode-side counterpart of allocation
    fragmentation."""
    bm = BlockManager(num_blocks=9, block_size=4)
    assert bm.gather_waste() == 0.0 and bm.peak_gather_waste == 0.0
    # 2 slots read at width 16 holding 4+8 useful -> waste 1 - 12/32
    assert bm.note_gather([4, 8], 16) == pytest.approx(1 - 12 / 32)
    # a tighter step: 2 slots at width 8 holding 7+8 -> 1 - 15/16
    assert bm.note_gather([7, 8], 8) == pytest.approx(1 - 15 / 16)
    assert bm.peak_gather_waste == pytest.approx(1 - 12 / 32)
    assert bm.gather_waste() == pytest.approx(1 - 27 / 48)
    assert bm.note_gather([], 16) == 0.0        # empty step: no-op


def test_block_manager_verify_waste_is_separate_from_gather_waste():
    """note_verify accounts width-(k+1) window padding (rejected draft
    tails) in ITS OWN accumulators — a speculative engine can have high
    verify waste with low bucket-read waste and vice versa, and the
    report must tell them apart."""
    bm = BlockManager(num_blocks=9, block_size=4)
    assert bm.verify_waste() == 0.0 and bm.peak_verify_waste == 0.0
    # 2 windows of width 5 committing 5 and 2 tokens -> 1 - 7/10
    assert bm.note_verify([5, 2], 5) == pytest.approx(1 - 7 / 10)
    # a fully-accepted step: zero waste, peak latched from before
    assert bm.note_verify([5, 5], 5) == 0.0
    assert bm.peak_verify_waste == pytest.approx(1 - 7 / 10)
    assert bm.verify_waste() == pytest.approx(1 - 17 / 20)
    assert bm.note_verify([], 5) == 0.0         # empty step: no-op
    # gather-side accumulators untouched
    assert bm.gather_waste() == 0.0 and bm.peak_gather_waste == 0.0


# -- ISSUE 8: copy-on-write prefix caching -----------------------------------

def test_block_manager_double_free_guard():
    """The satellite hard-guard: release()/free()/trim() on a block id
    that is no longer held raises instead of silently corrupting the
    free list (fatal once refcounts share blocks across requests)."""
    bm = BlockManager(num_blocks=9, block_size=4)
    got = bm.allocate(2)
    bm.release(got)
    with pytest.raises(ValueError, match="double free"):
        bm.release([got[0]])                     # already on the free list
    with pytest.raises(ValueError, match="double free"):
        bm.free([got[1]])                        # legacy alias, same guard
    # trim routes through release: a table holding an already-released
    # id must raise, not push the id onto the free list twice
    stale = [bm.allocate(1)[0], got[0]]
    with pytest.raises(ValueError, match="double free"):
        bm.trim(stale, 0)
    # a zero-ref CACHED block is not held either: releasing it again
    # must raise, not corrupt the LRU/free accounting
    t = bm.allocate(1)
    bm.register_prefix(np.arange(1, 5), t)
    bm.release(t)
    assert bm.num_cached == 1
    with pytest.raises(ValueError, match="double free"):
        bm.release(t)


def test_block_manager_prefix_match_register_lru_roundtrip():
    """The prefix-index lifecycle: register publishes full prompt
    blocks, match increfs them (chain-verified — a diverging prompt
    misses from the divergence block on), release parks zero-ref
    registered blocks in the LRU (reusable, counted as capacity), and
    allocation pressure evicts oldest-first, after which the lookup
    misses."""
    bm = BlockManager(num_blocks=8, block_size=4)     # 7 allocatable
    prompt = np.arange(1, 14)                         # 13 tokens, 3 full blocks
    table = bm.allocate(4)                            # ceil(13/4)
    bm.register_prefix(prompt, table)
    # another request with the same prompt start shares all 3 full blocks
    hit = bm.match_prefix(prompt)
    assert hit == table[:3]
    assert bm.blocks_saved() == 3                     # 3 dedup'd blocks
    # a prompt diverging INSIDE block 1 matches only block 0
    other = np.concatenate([prompt[:6], [99, 98, 97, 96]])
    hit2 = bm.match_prefix(other)
    assert hit2 == table[:1]
    bm.release(hit2)
    # a cap: the caller can bound the walk (engine leaves the final
    # prompt token uncached)
    assert bm.match_prefix(prompt, max_blocks=2) == table[:2]
    bm.release(table[:2])
    bm.release(hit)
    bm.release(table)                                 # original owner done
    assert bm.num_used == 0 and bm.num_cached == 3
    assert bm.can_allocate(7)                         # cached = capacity
    # pressure: allocating past the free list evicts oldest (block 0's
    # chunk) — the chain then misses at level 0, so NOTHING matches
    got = bm.allocate(5)
    assert bm.num_cached == 2 and bm.prefix_evictions == 1
    assert bm.match_prefix(prompt) == []
    bm.release(got)


def test_block_manager_privatize_cow_semantics():
    """privatize(): refcount > 1 => fresh private copy (src/dst device
    copy returned, source stays with the other holder); sole-owner
    registered => unpublish + write in place (no copy)."""
    bm = BlockManager(num_blocks=9, block_size=4)
    prompt = np.arange(1, 9)                          # 2 full blocks
    table = bm.allocate(2)
    bm.register_prefix(prompt, table)
    sharer = bm.match_prefix(prompt)                  # refs now 2/2
    copies = bm.privatize(sharer, 0, 1)
    assert len(copies) == 1 and copies[0][0] == table[0]
    assert sharer[0] != table[0] and bm.cow_copies == 1
    assert bm.is_private(sharer[0])
    # the source block is still the registered original at ref 1
    assert bm.match_prefix(prompt, max_blocks=1) == [table[0]]
    bm.release([table[0]])
    # sole-owner registered block: in-place unpublish, no copy
    bm.release(sharer)                                # drop the sharer refs
    bm.release([table[1]])                            # table now fully cached
    mine = bm.match_prefix(prompt)                    # revive both at ref 1
    assert bm.privatize(mine, 1, 2) == []
    assert bm.is_private(mine[1])                     # unregistered now
    assert bm.match_prefix(prompt, max_blocks=2) == [table[0]]
    bm.release([table[0]])
    bm.release(mine)
    bm.release([table[0]])                            # the allocate() ref
    assert bm.num_used == 0


def test_block_conservation_under_random_schedule(rng):
    """The satellite property test: across a randomized
    submit/admit/prefill/decode/preempt/finish/share/COW schedule with
    prefix caching on (small pool => LRU eviction pressure) PLUS the
    ISSUE 17 host tier (a stand-in spill/swap hook drives swap-out /
    swap-in / demote / revive / payload-evict through the same
    churn), every step preserves ``num_free + num_used + num_cached +
    num_hosted == num_blocks - 1``, every table reference is backed by
    exactly its refcount, no table references a freed block, and
    hosted blocks are never simultaneously free or held."""
    from collections import Counter
    from types import SimpleNamespace

    bm = BlockManager(num_blocks=20, block_size=4)
    # chunk 8 vs block 4: a cached prefix of 12 tokens re-aligns to
    # chunk 8, so admissions privatize (COW) the overlap block when the
    # original holder is still resident
    s = Scheduler(3, bm, 8, 32, prefix_cache=True)
    prefixes = [rng.randint(1, 100, (12,)).astype(np.int32),
                rng.randint(1, 100, (20,)).astype(np.int32)]
    # the host tier, engine-free: payloads are opaque (conservation is
    # about IDs, not bytes) and the budget is tight enough that
    # reserve failures and oldest-first payload eviction both happen
    bm.set_spill(lambda b: SimpleNamespace(nbytes=64), host_budget=1024)

    def swap_hook(slot):
        if not rng.randint(0, 2):
            return False                     # the recompute arm
        req = slot.request
        n = bm.blocks_for(slot.context_len)
        if n <= 0 or n > len(slot.table):
            return False
        if not bm.host_reserve(n * 64):
            return False                     # budget starved: recompute
        req.swap_set = SimpleNamespace(n_blocks=n, nbytes=n * 64)
        req.swap_context = slot.context_len
        return True

    s.swap_hook = swap_hook

    def check():
        assert (bm.num_free + bm.num_used + bm.num_cached
                + bm.num_hosted == bm.num_blocks - 1)
        held = Counter(b for slot in s.slots if not slot.free
                       for b in slot.table)
        refs = {b: bm._ref[b] for b in range(1, bm.num_blocks)
                if bm._ref[b] > 0}
        assert dict(held) == refs            # every ref is a table ref
        free_set = set(bm._free)
        assert not (set(held) & free_set)    # no table refs a freed block
        assert 0 not in held                 # the null block is never owned
        hosted = set(bm._hosted)
        assert not (hosted & free_set)       # demoted ids are resident
        assert not (hosted & set(held))      # ...and zero-ref

    for step in range(300):
        op = rng.randint(0, 6)
        if op == 5 and bm.host_tier_active:  # demotion pressure
            bm.demote(max_blocks=int(rng.randint(1, 3)))
        elif op == 0 and len(s.waiting) < 4:
            if rng.randint(0, 2):
                pre = prefixes[rng.randint(0, len(prefixes))]
                tail = rng.randint(1, 100,
                                   (rng.randint(1, 6),)).astype(np.int32)
                prompt = np.concatenate([pre, tail])
            else:
                prompt = rng.randint(
                    1, 100, (rng.randint(1, 16),)).astype(np.int32)
            try:
                s.submit(Request(prompt=prompt,
                                 max_new_tokens=int(rng.randint(1, 5))))
            except ValueError:
                pass                          # over-length: rejected
        elif op == 1:
            s.admit()
        elif op == 2:                         # one prefill chunk everywhere
            for slot in s.next_prefill_slots(3):
                slot.prefill_pos += s.prefill_chunk
                if slot.prefill_pos >= s.padded_prompt_len(slot.request):
                    s.finish_prefill(slot)
        elif op == 3:                         # one decode step
            try:
                s.ensure_decode_capacity()
            except PoolExhausted:
                pass
            for slot in s.decode_slots():
                req = slot.request
                slot.context_len += 1
                req.output.append(0)
                if len(req.output) >= req.max_new_tokens:
                    s.finish(slot)
        elif op == 4:                         # forced preemption
            ds = s.decode_slots()
            if ds:
                s.preempt(ds[int(rng.randint(0, len(ds)))])
        check()
    # drain: preempted/waiting requests release nothing further; every
    # running request's blocks come back on finish
    for slot in s.slots:
        if not slot.free:
            s.finish(slot)
    check()
    assert bm.num_used == 0


def _prefix_trace(rng, prefix_len, tails, max_news, vocab=120):
    """Requests sharing one random prefix with varied random tails."""
    prefix = rng.randint(1, vocab, (prefix_len,)).astype(np.int32)
    return [(np.concatenate([prefix,
                             rng.randint(1, vocab, (t,)).astype(np.int32)])
             if t else prefix.copy(), m)
            for t, m in zip(tails, max_news)]


def test_prefix_cache_serve_token_exact_with_forced_cow(gpt2_setup):
    """The tentpole exactness gate: shared-prefix serving is
    token-identical to cold start (greedy vs generate_causal), with
    real sharing (later requests' prefill skips cached chunks) AND
    forced copy-on-write — block_size 4 under chunk 8 re-aligns a
    12-token cached prefix to chunk 8, so a request diverging from a
    still-resident sharer mid-chunk must privatize the overlap block
    before scattering into it."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(21)
    # A long-running (max_new 14), then short riders sharing its
    # 12-token prefix admitted AFTER A registered — while A still
    # holds its blocks, so the overlap block's refcount is > 1
    trace = _prefix_trace(rng, 12, tails=[3, 0, 2, 1, 2],
                          max_news=[14, 2, 4, 3, 4])
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=2, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=32)
    assert eng.prefix_cache
    reqs = list(eng.finished.values())
    assert sum(r.prefix_cached_tokens for r in reqs) > 0   # real hits
    assert eng.blocks.cow_copies > 0                       # real COW
    assert eng.stats().cache_hit_rate > 0
    assert eng.stats().blocks_shared_peak > 0
    # conservation after the run: everything free or cached, none held
    assert eng.blocks.num_used == 0
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)


def test_prefix_cache_exact_under_preemption_of_sharing_request(gpt2_setup):
    """Forced recompute preemption OF a prefix-sharing request: only
    its private references release (other holders and the cache keep
    the shared blocks), the resumed request re-hits the cache for its
    folded prompt, and every stream stays token-exact."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(22)
    trace = _prefix_trace(rng, 12, tails=[2, 3, 1, 2, 3],
                          max_news=[12, 12, 12, 12, 12])
    # 11 allocatable blocks of 4 for five 14-15 token prompts that each
    # want 12 more: preemption is forced even WITH sharing
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=4, block_size=4, num_blocks=12,
                               prefill_chunk=8, max_model_len=32)
    assert eng.stats().preemptions > 0
    assert sum(r.prefix_cached_tokens
               for r in eng.finished.values()) > 0
    assert eng.blocks.num_used == 0
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)


def test_prefix_cache_speculative_serve_exact(gpt2_setup, spec_draft):
    """Prefix caching composes with speculative decode: the draft's
    pools ride the same shared block tables (COW copies apply to both
    address spaces), greedy stays token-exact, and the verify-window
    trim never releases a shared block."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(23)
    trace = _prefix_trace(rng, 12, tails=[3, 0, 2, 1], max_news=[12, 3, 5, 4])
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=2, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=32,
                               speculate_k=2, draft=spec_draft)
    assert sum(r.prefix_cached_tokens
               for r in eng.finished.values()) > 0
    assert eng.stats().draft_proposed > 0
    assert eng.blocks.num_used == 0
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)


def test_prefix_cache_off_matches_on_and_stays_cold(gpt2_setup):
    """The regression-tax gate: prefix_cache='off' serves the exact
    same tokens as 'on' (and the cold reference), never touches the
    index/LRU/COW machinery, and a sampled trace stays bitwise
    seed-identical across on/off — the cache must be semantically
    invisible either way."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(24)
    trace = _prefix_trace(rng, 12, tails=[3, 1, 2, 2], max_news=[8, 6, 7, 5])
    kws = [dict(), dict(temperature=0.9, top_k=20, top_p=0.9, seed=7),
           dict(), dict(temperature=0.7, seed=3)]

    def run(prefix_cache):
        eng = ServeEngine(model, params, num_slots=3, block_size=4,
                          num_blocks=40, prefill_chunk=8,
                          max_model_len=32, prefix_cache=prefix_cache)
        reqs = [eng.submit(p, m, **kw)
                for (p, m), kw in zip(trace, kws)]
        eng.run()
        return [[int(t) for t in eng.output_ids(r)] for r in reqs], eng

    on, eng_on = run("on")
    off, eng_off = run("off")
    assert on == off
    assert not eng_off.prefix_cache
    assert eng_off.blocks.num_cached == 0          # machinery inert
    assert eng_off.blocks.cow_copies == 0
    assert eng_off.blocks.peak_shared_blocks == 0
    assert all(r.prefix_cached_tokens == 0
               for r in eng_off.finished.values())
    assert eng_off.stats().cache_hit_rate is None
    # off: every block comes straight back to the free list (PR 6
    # behavior byte-for-byte)
    assert eng_off.blocks.num_free == eng_off.blocks.num_blocks - 1
    # the greedy rows also equal the cold per-request reference
    for (p, m), kw, out in zip(trace, kws, on):
        if not kw:
            assert out == _reference(model, params, p, m,
                                     cfg.eos_token_id)


def test_parse_prefix_cache_knob(monkeypatch):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_PREFIX_CACHE,
        parse_prefix_cache,
    )

    assert parse_prefix_cache(None) is True        # default on
    assert parse_prefix_cache("off") is False
    assert parse_prefix_cache("on") is True
    assert parse_prefix_cache(False) is False
    monkeypatch.setenv(ENV_PREFIX_CACHE, "off")
    assert parse_prefix_cache(None) is False
    monkeypatch.setenv(ENV_PREFIX_CACHE, "banana")
    with pytest.raises(ValueError, match="unparseable"):
        parse_prefix_cache(None)


def test_scheduler_lookahead_reserves_verify_window():
    """decode_lookahead generalizes the +1 decode reservation: submit
    rejects requests whose window would overflow max_model_len, and
    ensure_decode_capacity grows tables to context + lookahead."""
    bm = BlockManager(num_blocks=20, block_size=4)
    s = Scheduler(1, bm, 4, 32, decode_lookahead=4)     # k = 3
    with pytest.raises(ValueError, match="verify-window"):
        s.submit(Request(prompt=np.arange(1, 9), max_new_tokens=22))
    s.submit(Request(prompt=np.arange(1, 9), max_new_tokens=21))
    s.admit()
    slot = s.slots[0]
    s.finish_prefill(slot)
    assert s.max_decode_context() == 8 + 4
    s.ensure_decode_capacity()
    # table covers context + lookahead = 12 tokens -> 3 blocks
    assert len(slot.table) == 3


# -- ISSUE 9: fused paged-attention kernel + int8 KV pools -------------------

def _int8_model(model, cfg):
    import dataclasses

    return type(model)(dataclasses.replace(cfg, kv_cache_dtype="int8"))


def test_engine_exact_with_pallas_kernel(gpt2_setup):
    """The ISSUE 9 tentpole gate: with the fused Pallas decode kernel
    engaged (interpret mode on CPU), the engine stays token-for-token
    generate_causal — across bucket boundaries, with the kv-bytes
    telemetry flowing."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(11)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(5, 6), (15, 5), (9, 4)]]
    eng = _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                               num_slots=3, block_size=4, num_blocks=40,
                               prefill_chunk=8, max_model_len=64,
                               gather_buckets=[16, 64], kernel="pallas")
    assert eng.kernel == "pallas"
    slo = eng.slo_summary()
    assert slo["kernel"] == "pallas" and slo["kv_dtype"] == "fp"
    assert slo["kv_bytes_read_per_step"] > 0
    assert eng.stats().kv_bytes_read > 0


def test_engine_exact_int8_pools_under_preemption(gpt2_setup):
    """int8 KV pools (the removed rejection): engine output is
    token-exact vs generate_causal on the SAME int8-cache config,
    including under forced recompute preemption — quantization is
    deterministic, so the re-prefilled pools are bitwise identical."""
    cfg, model, params = gpt2_setup
    int8 = _int8_model(model, cfg)
    rng = np.random.RandomState(12)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(4)]
    eng = _assert_engine_exact(int8, params, trace, cfg.eos_token_id,
                               num_slots=4, block_size=4, num_blocks=10,
                               prefill_chunk=8, max_model_len=32)
    assert eng.stats().preemptions > 0
    assert eng.kv_cache_dtype == "int8"
    # int8 + fp32-scale pools cost fewer bytes/token than fp pools
    fp_eng = _assert_engine_exact(model, params, [trace[0]],
                                  cfg.eos_token_id, num_slots=1,
                                  block_size=4, num_blocks=10,
                                  prefill_chunk=8, max_model_len=32)
    assert eng.blocks.token_bytes < fp_eng.blocks.token_bytes


def test_engine_int8_composes_with_speculative_and_prefix(gpt2_setup):
    """int8 pools through BOTH riders: the draft/verify window path
    (scale planes scatter with the window writes, rewind hides stale
    scales with stale values) and prefix-cache sharing (shared blocks
    carry int8 + scales; a primed template re-serves exactly)."""
    cfg, model, params = gpt2_setup
    int8 = _int8_model(model, cfg)
    rng = np.random.RandomState(13)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(5, 8), (9, 6), (7, 7)]]
    eng = _assert_engine_exact(int8, params, trace, cfg.eos_token_id,
                               num_slots=2, block_size=4, num_blocks=60,
                               prefill_chunk=8, max_model_len=64,
                               speculate_k=3, draft=1)
    assert {str(p.dtype) for p in eng._d_pools} == {"int8", "float32"}
    assert eng.stats().draft_proposed > 0

    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    shared = rng.randint(1, 120, (12,)).astype(np.int32)
    tails = [(np.concatenate([shared,
                              rng.randint(1, 120, (t,)).astype(np.int32)]),
              5) for t in (3, 5, 2)]
    eng2 = ServeEngine(int8, params, num_slots=3, block_size=4,
                       num_blocks=40, prefill_chunk=8, max_model_len=64,
                       prefix_cache=True)
    eng2.submit(shared, 1)
    eng2.run()                            # prime the template
    reqs = [eng2.submit(p, m) for p, m in tails]
    eng2.run()
    for (p, m), r in zip(tails, reqs):
        got = [int(t) for t in eng2.output_ids(r)]
        assert got == _reference(int8, params, p, m, cfg.eos_token_id)
    assert eng2.blocks.peak_shared_blocks > 0


def test_engine_serves_sliding_window_llama():
    """The removed sliding-window rejection: a Mistral-style windowed
    GQA config serves token-exact vs its own generate_causal (the
    window bands from logical positions on the gathered path)."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128, eos_token_id=127,
                      pad_token_id=0, dtype=jnp.float32,
                      sliding_window=12)
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg, seed=0)
    rng = np.random.RandomState(14)
    # continuations push contexts PAST the window so banding engages
    trace = [(rng.randint(3, 120, (p,)).astype(np.int32), m)
             for p, m in [(6, 10), (11, 8)]]
    _assert_engine_exact(model, params, trace, cfg.eos_token_id,
                         num_slots=2, block_size=8, num_blocks=20,
                         prefill_chunk=8, max_model_len=64)


def test_engine_sliding_window_pallas_int8_llama():
    """The full ISSUE 9 composition on the hardest config: windowed
    GQA Llama served through the fused kernel over int8 pools — the
    kernel's banded tile-skip, GQA grouping, and in-tile dequant all
    engaged at once, still token-exact vs generate_causal on the
    matching int8 config."""
    import dataclasses

    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_position_embeddings=128, eos_token_id=127,
                      pad_token_id=0, dtype=jnp.float32,
                      sliding_window=12)
    model = LlamaForCausalLM(cfg)
    params = init_params(model, cfg, seed=0)
    int8 = LlamaForCausalLM(dataclasses.replace(cfg,
                                                kv_cache_dtype="int8"))
    rng = np.random.RandomState(15)
    trace = [(rng.randint(3, 120, (p,)).astype(np.int32), m)
             for p, m in [(6, 10), (11, 8)]]
    eng = _assert_engine_exact(int8, params, trace, cfg.eos_token_id,
                               num_slots=2, block_size=8, num_blocks=20,
                               prefill_chunk=8, max_model_len=64,
                               kernel="pallas", gather_buckets=[24, 64])
    assert eng.kernel == "pallas" and eng.kv_cache_dtype == "int8"


def test_kv_pool_bytes_doubles_int8_admission(gpt2_setup):
    """The capacity-accounting satellite: pools sized by the SAME byte
    budget hold ~2x (with scale overhead, >=2x at D=16... exactly
    token_bytes-proportionally) more blocks under int8 — and through
    the scheduler's block-denominated admission math, more resident
    requests — instead of inheriting fp-sized reservations."""
    cfg, model, params = gpt2_setup
    int8 = _int8_model(model, cfg)
    rng = np.random.RandomState(16)
    trace = [(rng.randint(1, 120, (8,)).astype(np.int32), 8)
             for _ in range(6)]
    budget = None
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    probe = ServeEngine(model, params, num_slots=6, block_size=4,
                        num_blocks=8, prefill_chunk=8, max_model_len=32)
    # budget = exactly 5 fp blocks' worth of pool bytes
    budget = 5 * probe.blocks.block_bytes
    fp_eng = _assert_engine_exact(model, params, trace,
                                  cfg.eos_token_id, num_slots=6,
                                  block_size=4, prefill_chunk=8,
                                  max_model_len=32,
                                  kv_pool_bytes=budget)
    int8_eng = _assert_engine_exact(int8, params, trace,
                                    cfg.eos_token_id, num_slots=6,
                                    block_size=4, prefill_chunk=8,
                                    max_model_len=32,
                                    kv_pool_bytes=budget)
    assert fp_eng.blocks.num_blocks == 6          # 1 + 5
    assert int8_eng.blocks.num_blocks >= 2 * fp_eng.blocks.num_blocks - 1
    assert (int8_eng.stats().peak_resident_requests
            >= 2 * fp_eng.stats().peak_resident_requests)


def test_parse_kernel_and_kv_dtype_knobs(monkeypatch):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_KERNEL,
        ENV_KV_DTYPE,
        parse_kernel,
        parse_kv_dtype,
    )

    assert parse_kernel(None) == "xla"
    assert parse_kernel("PALLAS") == "pallas"
    monkeypatch.setenv(ENV_KERNEL, "pallas")
    assert parse_kernel(None) == "pallas"
    with pytest.raises(ValueError, match="xla | pallas"):
        parse_kernel("triton")
    assert parse_kv_dtype(None, "fp") == "fp"
    assert parse_kv_dtype(None, "int8") == "int8"
    assert parse_kv_dtype("int8", "fp") == "int8"
    monkeypatch.setenv(ENV_KV_DTYPE, "int8")
    assert parse_kv_dtype(None, "fp") == "int8"
    with pytest.raises(ValueError, match="fp | int8"):
        parse_kv_dtype("fp16", "fp")


# -- dispatch-ahead serving loop (ISSUE 12) ----------------------------------

def _run_overlap_pair(model, params, trace, kws=None, **engine_kw):
    """Serve the same trace twice — ``overlap`` off then on — and
    return (off_outputs, on_outputs, on_engine). The exactness torture
    harness: the pipelined loop must be semantically invisible."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    kws = kws or [dict() for _ in trace]
    outs = {}
    engines = {}
    for mode in ("off", "on"):
        eng = ServeEngine(model, params, overlap=mode, **engine_kw)
        reqs = [eng.submit(p, m, **kw) for (p, m), kw in zip(trace, kws)]
        eng.run()
        outs[mode] = [[int(t) for t in eng.output_ids(r)] for r in reqs]
        engines[mode] = eng
    assert engines["off"].overlap_flushes == 0    # serial never drains
    return outs["off"], outs["on"], engines["on"]


def test_overlap_exact_with_eos_on_inflight_iteration(gpt2_setup):
    """EOS lands while the next iteration is already in flight (the
    dispatch-ahead loop discovers a finish one step LATE and must
    discard the wasted in-flight token): rebuild the model so EOS is a
    token the reference actually emits mid-stream, serve a multi-slot
    trace, and require overlap-on output == overlap-off output ==
    generate_causal, token for token."""
    import dataclasses

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(21)
    prompts = [rng.randint(1, 120, (p,)).astype(np.int32)
               for p in (5, 9, 12, 7)]
    # EOS = the 3rd greedy continuation token of prompt 0: that request
    # finishes mid-decode with other slots still running, so the finish
    # is always discovered with a dispatch in flight
    ref = _reference(model, params, prompts[0], 12, eos=-1)
    eos_cfg = dataclasses.replace(cfg, eos_token_id=int(ref[2]))
    eos_model = type(model)(eos_cfg)
    trace = [(p, 12) for p in prompts]
    off, on, eng = _run_overlap_pair(
        eos_model, params, trace, num_slots=4, block_size=4,
        num_blocks=60, prefill_chunk=8, max_model_len=64)
    assert on == off
    assert eng.overlap
    for (p, m), got in zip(trace, on):
        assert got == _reference(eos_model, params, p, m,
                                 eos_cfg.eos_token_id)


def test_overlap_exact_across_bucket_switches(gpt2_setup):
    """Bucket grow mid-pipeline: contexts crossing the 16-wide first
    bucket while dispatches are in flight — the bucket choice is
    re-derived from exact counts (context advances at dispatch), so
    the switch needs no flush and changes no tokens."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(22)
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), 9)
             for p in (15, 16, 17, 5)]
    off, on, eng = _run_overlap_pair(
        model, params, trace, num_slots=4, block_size=4, num_blocks=60,
        prefill_chunk=8, max_model_len=64, gather_buckets=[16, 32])
    assert on == off
    assert eng.bucket_switches > 0          # the ladder really moved
    assert eng.overlap_flushes == 0         # growth is count-derived


def test_overlap_exact_under_forced_preemption_and_flushes(gpt2_setup):
    """The mandatory flush: KV pressure / preemption must act on
    committed state, so the pipeline drains first (overlap_flushes
    latches it) and recompute preemption stays token-invisible."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(5)]
    off, on, eng = _run_overlap_pair(
        model, params, trace, num_slots=4, block_size=4, num_blocks=10,
        prefill_chunk=8, max_model_len=32)
    assert on == off
    assert eng.stats().preemptions > 0
    assert eng.overlap_flushes > 0          # the drain was mandatory
    assert eng.stats().overlap_flushes == eng.overlap_flushes


def test_overlap_sampled_bitwise_and_spec_rejection_storm(gpt2_setup,
                                                          spec_draft):
    """The remaining torture axes in one composition: (a) sampled
    streams stay bitwise identical across the pipeline (fold indices
    re-derived through the in-flight count), and (b) a speculative
    engine under an adversarial draft (rejection storm) + tight-pool
    preemption — where the window commit is the pipeline boundary —
    is token-identical with overlap on vs off."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(23)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(4)]
    kws = [dict(temperature=0.9, top_k=20, top_p=0.9, seed=s)
           for s in (1, 2, 3)] + [dict()]
    off, on, _ = _run_overlap_pair(
        model, params, trace, kws=kws, num_slots=3, block_size=4,
        num_blocks=40, prefill_chunk=8, max_model_len=32)
    assert on == off                        # bitwise, greedy rider too
    # speculative rejection storm + preemption, overlap on vs off
    off_s, on_s, eng = _run_overlap_pair(
        model, params, trace, num_slots=4, block_size=4, num_blocks=11,
        prefill_chunk=8, max_model_len=32, speculate_k=2,
        draft=spec_draft)
    assert on_s == off_s
    stats = eng.stats()
    assert stats.preemptions > 0
    assert 0 <= stats.acceptance_rate < 1   # rejections actually hit
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)
    assert eng.blocks.num_used == 0


def test_generated_tail_registers_resubmit_hits_cache(gpt2_setup):
    """PR 7a follow-up: a finished request's GENERATED tail joins the
    prefix index, so agentic multi-turn traffic that re-submits its
    own completion as the next prompt hits the cache past the original
    prompt — exactness vs a cold generate_causal + a nonzero hit rate
    covering generated blocks are both required."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(24)
    prompt = rng.randint(1, 120, (12,)).astype(np.int32)
    eng = ServeEngine(model, params, num_slots=2, block_size=4,
                      num_blocks=40, prefill_chunk=4, max_model_len=64)
    first = eng.submit(prompt, 12)
    eng.run()
    out1 = eng.output_ids(first)
    assert len(out1) == 12                  # no EOS: full continuation
    # the agentic turn: the client folds its completion into the next
    # prompt. blocks_for(prompt+output minus the partial tail) of the
    # FIRST request's blocks are now indexed — including generated
    # ones past the 12-token prompt
    follow = np.concatenate([prompt, out1]).astype(np.int32)
    second = eng.submit(follow, 6)
    eng.run()
    got = [int(t) for t in eng.output_ids(second)]
    assert got == _reference(model, params, follow, 6, cfg.eos_token_id)
    # the cached span covers GENERATED tokens: more than the original
    # prompt's full blocks were served from cache
    assert second.prefix_cached_tokens > (len(prompt) // 4) * 4
    assert second.cache_hit_rate > 0
    assert eng.stats().cache_hit_rate > 0


def test_generated_tail_registration_is_partial_block_safe(gpt2_setup):
    """Only FULL aligned blocks of the finished sequence are
    published: a short continuation that never completes a block adds
    nothing to the index (and the conservation invariant holds with
    the finished request's blocks parked in the cache LRU)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(25)
    prompt = rng.randint(1, 120, (8,)).astype(np.int32)
    eng = ServeEngine(model, params, num_slots=2, block_size=4,
                      num_blocks=40, prefill_chunk=4, max_model_len=64)
    req = eng.submit(prompt, 2)             # ctx 9: blocks 0..1 full
    eng.run()
    # full blocks of (prompt + 2 generated)[:9] = 2; both indexable
    assert eng.blocks.num_cached == 2
    assert (eng.blocks.num_free + eng.blocks.num_cached
            == eng.blocks.num_blocks - 1)
    assert eng.blocks.num_used == 0
    assert req.rid in eng.finished


def test_parse_overlap_knob(monkeypatch):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_OVERLAP,
        parse_overlap,
    )

    assert parse_overlap(None) is True      # default on
    assert parse_overlap("off") is False
    assert parse_overlap("on") is True
    assert parse_overlap(False) is False
    monkeypatch.setenv(ENV_OVERLAP, "off")
    assert parse_overlap(None) is False
    monkeypatch.setenv(ENV_OVERLAP, "1")
    assert parse_overlap(None) is True
    with pytest.raises(ValueError, match=ENV_OVERLAP):
        parse_overlap("sometimes")


# -- ISSUE 13: tensor-parallel serving engine --------------------------------

def _run_tp_pair(model, params, trace, tp=2, **engine_kw):
    """Serve the same trace on a single-device engine and a TP-mesh
    engine (the 8-fake-CPU-device conftest backend); returns
    (base_outputs, tp_outputs, tp_engine). The tentpole gate: sharding
    must be semantically invisible — token-identical output."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    outs = {}
    engines = {}
    for mesh in (None, tp):
        eng = ServeEngine(model, params, mesh=mesh, **engine_kw)
        reqs = [eng.submit(p, m) for p, m in trace]
        eng.run()
        outs[mesh] = [[int(t) for t in eng.output_ids(r)] for r in reqs]
        engines[mesh] = eng
    assert engines[None].tp == 1 and engines[None].mesh is None
    assert engines[tp].tp == tp and engines[tp].mesh is not None
    return outs[None], outs[tp], engines[tp]


def test_tp_engine_token_exact_across_bucket_boundary(gpt2_setup,
                                                      devices8):
    """The ISSUE 13 tier-1 exactness gate, half 1: a TP=2 engine
    (params Megatron-sharded, every KV pool sharded on heads) emits
    token-identical output to the TP=1 engine across a gather-bucket
    boundary — and its per-device KV accounting is half the model's."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(31)
    # contexts cross the 16-wide first bucket mid-decode
    trace = [(rng.randint(1, 120, (p,)).astype(np.int32), m)
             for p, m in [(5, 9), (15, 6), (12, 8)]]
    base, tp, eng = _run_tp_pair(
        model, params, trace, num_slots=3, block_size=4, num_blocks=40,
        prefill_chunk=8, max_model_len=32, gather_buckets=[16, 32])
    assert tp == base
    assert eng.bucket_switches > 0          # the boundary really moved
    # per-device re-denomination: each of the 2 shards holds half the
    # heads, so bytes/token halves vs the model's own figure
    # (num_layers × K+V × hidden × 4 bytes fp32)
    assert eng.blocks.token_bytes * 2 == \
        cfg.num_layers * 2 * cfg.hidden_size * 4
    slo = eng.slo_summary()
    assert slo["tp"] == 2
    assert slo["kv_pool_bytes_per_device"] == eng.blocks.pool_bytes


def test_tp_engine_token_exact_under_forced_preemption(gpt2_setup,
                                                       devices8):
    """The ISSUE 13 tier-1 exactness gate, half 2: recompute
    preemption on the sharded engine — re-prefill over sharded pools
    reproduces the stream exactly, and the per-device byte figure is
    half the single-device engine's on the same geometry."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(5)]
    base, tp, eng = _run_tp_pair(
        model, params, trace, num_slots=4, block_size=4, num_blocks=10,
        prefill_chunk=8, max_model_len=32)
    assert tp == base
    assert eng.stats().preemptions > 0
    assert eng.stats().tp == 2
    # same block geometry, half the bytes per device
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    lone = ServeEngine(model, params, num_slots=4, block_size=4,
                       num_blocks=10, prefill_chunk=8, max_model_len=32)
    assert eng.blocks.token_bytes * 2 == lone.blocks.token_bytes
    assert eng.blocks.pool_bytes * 2 == lone.blocks.pool_bytes


def test_tp_engine_kv_pool_bytes_budget_doubles_admission(gpt2_setup,
                                                          devices8):
    """The capacity story the bench line gates, as a unit test: on the
    SAME per-device ``kv_pool_bytes`` budget a TP=2 engine holds ~2x
    the blocks and keeps ~2x the requests concurrently resident
    (uniform block need: prompts pad to one chunk, continuations fit
    the padded span)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(32)
    trace = [(rng.randint(1, 120, (6,)).astype(np.int32), 2)
             for _ in range(8)]
    lone = ServeEngine(model, params, num_slots=1, block_size=4,
                       num_blocks=4, prefill_chunk=8, max_model_len=32)
    budget = 4 * 4 * lone.blocks.token_bytes     # 4 blocks single-device
    kw = dict(num_slots=6, block_size=4, num_blocks=999, prefill_chunk=8,
              max_model_len=32, kv_pool_bytes=budget)
    engs = {}
    for mesh in (None, 2):
        eng = ServeEngine(model, params, mesh=mesh, **kw)
        reqs = [eng.submit(p, m) for p, m in trace]
        eng.run()
        engs[mesh] = (eng, [[int(t) for t in eng.output_ids(r)]
                            for r in reqs])
    base, tp = engs[None][0], engs[2][0]
    assert engs[2][1] == engs[None][1]
    assert base.blocks.num_blocks == 5 and tp.blocks.num_blocks == 9
    assert tp.peak_resident >= 2 * base.peak_resident
    # same per-device budget — the pools cost each chip the same bytes
    assert tp.blocks.pool_bytes <= budget + tp.blocks.block_bytes


@pytest.mark.slow
def test_tp_engine_speculative_prefix_int8_composition(gpt2_setup,
                                                       devices8):
    """The sharded engine under ALL the riders at once (ISSUE 13 slow
    tier): speculative draft/verify (draft pools sharded over the same
    mesh), copy-on-write prefix caching (shard-local block copies),
    and int8 pools (scale pools shard on their heads axis too) —
    token-identical to the same composition single-device."""
    cfg, model, params = gpt2_setup
    int8 = _int8_model(model, cfg)
    rng = np.random.RandomState(33)
    shared = rng.randint(1, 120, (8,)).astype(np.int32)
    trace = [(np.concatenate([shared,
                              rng.randint(1, 120, (t,)).astype(np.int32)]),
              6) for t in (5, 3, 4, 6)]
    base, tp, eng = _run_tp_pair(
        model, params, trace, num_slots=3, block_size=4, num_blocks=60,
        prefill_chunk=8, max_model_len=48, speculate_k=2, draft=1,
        prefix_cache=True, kv_cache_dtype="int8")
    assert tp == base
    stats = eng.stats()
    assert stats.tp == 2
    assert stats.draft_proposed > 0
    assert stats.prefix_cached_tokens > 0   # the template really hit
    assert {str(p.dtype) for p in eng._pools} == {"int8", "float32"}
    # the draft's pools shard like the target's
    assert eng._d_plan.kv_shardings and eng._plan.kv_shardings


@pytest.mark.slow
def test_tp_sampled_serve_seed_deterministic_across_preemption(
        gpt2_setup, devices8):
    """ISSUE 13 acceptance, sampled half: streams on the SHARDED
    engine are bitwise seed-reproducible — a rerun with identical
    seeds reproduces identical tokens, and tight-pool recompute
    preemption changes nothing. (Cross-sharding identity is a GREEDY
    contract only: TP's row-parallel reductions reorder float sums, so
    sampled warp thresholds may differ in ulps between TP degrees —
    what is gated here is determinism OF the sharded engine.)"""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(35)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(4)]
    kws = [dict(temperature=0.9, top_k=20, top_p=0.9, seed=s)
           for s in (1, 2, 3)] + [dict()]

    def run(num_blocks):
        eng = ServeEngine(model, params, mesh=2, num_slots=3,
                          block_size=4, num_blocks=num_blocks,
                          prefill_chunk=8, max_model_len=32)
        reqs = [eng.submit(p, m, **kw) for (p, m), kw in zip(trace, kws)]
        eng.run()
        return [[int(t) for t in eng.output_ids(r)] for r in reqs], eng

    base, eng = run(40)
    assert eng.tp == 2
    again, _ = run(40)
    assert again == base                    # bitwise reproducible
    tight, teng = run(9)                    # tight pool: preemption
    assert teng.stats().preemptions > 0
    assert tight == base                    # preemption-invariant


def test_tp_engine_rejections_and_knob(gpt2_setup, devices8,
                                       monkeypatch):
    """The loud-rejection contracts: non-dividing kv heads (GQA), the
    pallas kernel, and the ``HSTD_SERVE_TP`` parsing rules."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_TP,
        ServeEngine,
        parse_tp,
    )

    cfg, model, params = gpt2_setup
    kw = dict(num_slots=2, block_size=4, num_blocks=20, prefill_chunk=8,
              max_model_len=32)
    # GQA: it is the KV heads that must divide — 2 kv heads cannot
    # shard over tensor=4
    lcfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=4, num_kv_heads=2, intermediate_size=64,
                       max_position_embeddings=128, eos_token_id=127,
                       pad_token_id=0, dtype=jnp.float32)
    lmodel = LlamaForCausalLM(lcfg)
    lparams = init_params(lmodel, lcfg, seed=0)
    with pytest.raises(ValueError, match="kv heads"):
        ServeEngine(lmodel, lparams, mesh=4, **kw)
    # ... and the SAME config serves fine at tp=2 (kv heads divide)
    eng = ServeEngine(lmodel, lparams, mesh=2, **kw)
    assert eng.tp == 2
    with pytest.raises(ValueError, match="pallas"):
        ServeEngine(model, params, mesh=2, kernel="pallas", **kw)
    # knob parsing
    assert parse_tp(None) == 1
    assert parse_tp(2) == 2
    assert parse_tp("4") == 4
    monkeypatch.setenv(ENV_TP, "2")
    assert parse_tp(None) == 2
    monkeypatch.setenv(ENV_TP, "")
    assert parse_tp(None) == 1
    with pytest.raises(ValueError, match=ENV_TP):
        parse_tp("two")
    with pytest.raises(ValueError, match=ENV_TP):
        parse_tp(0)


# -- ISSUE 13 satellite: low-load dispatch-ahead auto-flush ------------------

def test_overlap_lone_stream_auto_flushes_to_serial(gpt2_setup,
                                                    monkeypatch):
    """PR 12 follow-up: with decode occupancy 1 and an empty queue the
    dispatch-ahead pipeline auto-flushes — a lone stream commits every
    token in the iteration that dispatched it (no one-iteration
    deferred fetch on any token, and no trailing drain iteration), so
    last-token latency matches ``overlap='off'`` structurally:
    identical iteration count, identical tokens, zero pipeline
    dispatches. Telemetry elsewhere is unchanged — a concurrent trace
    still engages the pipeline (control below)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(34)
    prompt = rng.randint(1, 120, (9,)).astype(np.int32)
    kw = dict(num_slots=3, block_size=4, num_blocks=40, prefill_chunk=8,
              max_model_len=64)
    calls = []
    orig = ServeEngine._dispatch_decode
    monkeypatch.setattr(ServeEngine, "_dispatch_decode",
                        lambda self: (calls.append(1), orig(self))[1])
    off = ServeEngine(model, params, overlap=False, **kw)
    r_off = off.submit(prompt, 8)
    off.run()
    on = ServeEngine(model, params, overlap=True, **kw)
    r_on = on.submit(prompt, 8)
    on.run()
    assert not calls                        # never pipelined
    assert on.overlap and on.overlap_flushes == 0
    # last-token latency parity: the pipelined loop would need one
    # extra iteration to drain the final in-flight dispatch
    assert on.iterations == off.iterations
    assert list(on.output_ids(r_on)) == list(off.output_ids(r_off))
    # control: occupancy > 1 re-engages the pipeline, tokens unchanged
    calls.clear()
    trace = [(rng.randint(1, 120, (7,)).astype(np.int32), 6)
             for _ in range(3)]
    off2, on2, eng2 = _run_overlap_pair(model, params, trace, **kw)
    assert on2 == off2
    assert calls                            # dispatch-ahead really ran


# -- ISSUE 17: KV host tier (swap preemption + prefix demotion) --------------

def test_extract_insert_blocks_roundtrip_bitwise():
    """The tentpole's standalone unit gate: ``extract_blocks`` /
    ``insert_blocks`` round-trip a block set bitwise — value pools AND
    int8-style scale pools travel atomically — into the SAME or
    DIFFERENT destination ids, and the pair never touches the
    BlockManager (no refcount or free-list movement: pool I/O and
    block accounting are separate layers by design)."""
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
        extract_blocks,
        insert_blocks,
    )

    rng = np.random.RandomState(17)
    nb, bs = 12, 4
    # an int8-mode pool family: int8 values + fp32 scale planes
    pools = (
        jnp.asarray(rng.randn(nb, bs, 2, 3).astype(np.float32)),
        jnp.asarray(rng.randint(-128, 128, (nb, bs, 2, 3), np.int32)
                    .astype(np.int8)),
        jnp.asarray(rng.randn(nb, bs, 2).astype(np.float32)),
    )
    d_pools = (jnp.asarray(rng.randn(nb, bs, 2, 3).astype(np.float32)),)
    before = [np.asarray(p) for p in pools]

    bm = BlockManager(num_blocks=nb, block_size=bs)
    src = bm.allocate(3)
    free0, used0 = bm.num_free, bm.num_used
    snapshot = list(bm._free)

    bset = extract_blocks(pools, src, d_pools=d_pools)
    assert bset.n_blocks == 3 and bset.nbytes > 0
    # scatter into different ids on zeroed pools: bitwise per block
    dst = [b for b in range(1, nb) if b not in src][:3]
    zero = tuple(jnp.zeros_like(p) for p in pools)
    zero_d = tuple(jnp.zeros_like(p) for p in d_pools)
    out, out_d = insert_blocks(zero, bset, dst, d_pools=zero_d)
    for pi, p in enumerate(out):
        got = np.asarray(p)
        for s, d in zip(src, dst):
            np.testing.assert_array_equal(got[d], before[pi][s])
            assert got[d].dtype == before[pi][s].dtype
        # untouched rows stay zero
        other = [b for b in range(nb) if b not in dst]
        assert not np.asarray(p)[other].any()
    for s, d in zip(src, dst):
        np.testing.assert_array_equal(
            np.asarray(out_d[0])[d], np.asarray(d_pools[0])[s])
    # round-trip into the SAME ids reproduces the original pools
    back, _ = insert_blocks(zero, bset, src, d_pools=zero_d)
    for pi, p in enumerate(back):
        for s in src:
            np.testing.assert_array_equal(np.asarray(p)[s], before[pi][s])
    # the manager never moved: extraction is not an eviction
    assert (bm.num_free, bm.num_used) == (free0, used0)
    assert list(bm._free) == snapshot
    bm.release(src)
    assert bm.num_used == 0
    # shape mismatches are loud
    with pytest.raises(ValueError):
        insert_blocks(zero, bset, dst[:2])
    with pytest.raises(ValueError):
        insert_blocks(zero, bset, dst)      # draft payloads, no d_pools


def _run_swap(model, params, trace, swap, kws=None, swap_bytes=None,
              **engine_kw):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    kws = kws or [dict() for _ in trace]
    eng = ServeEngine(model, params, swap=swap, swap_bytes=swap_bytes,
                      **engine_kw)
    reqs = [eng.submit(p, m, **kw) for (p, m), kw in zip(trace, kws)]
    eng.run()
    return [[int(t) for t in eng.output_ids(r)] for r in reqs], eng


def test_swap_preemption_token_exact_greedy(gpt2_setup):
    """The ISSUE 17 exactness gate, greedy arm: on the forced-preemption
    trace a swapped-and-restored request is token-identical to the
    recompute path AND to generate_causal (= the unpreempted answer),
    with overlap ON and the pipeline provably drained before every
    extraction; the swap path really ran (outs/ins/tokens-avoided all
    positive) and a starved byte budget falls back to recompute, still
    exact."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(1)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(5)]
    kw = dict(num_slots=4, block_size=4, num_blocks=10, prefill_chunk=8,
              max_model_len=32)
    swp, eng = _run_swap(model, params, trace, "always", **kw)
    rec, rec_eng = _run_swap(model, params, trace, "never", **kw)
    assert swp == rec
    for (p, m), got in zip(trace, swp):
        assert got == _reference(model, params, p, m, cfg.eos_token_id)
    st = eng.stats()
    assert st.preemptions > 0 and rec_eng.stats().preemptions > 0
    assert st.swap_outs > 0 and st.swap_ins > 0
    assert st.recompute_tokens_avoided > 0 and st.swap_bytes > 0
    assert st.swap_policy == "always"
    assert rec_eng.stats().swap_outs == 0   # never = recompute arm
    # overlap pipeline drained before extraction (the default loop ran)
    assert eng.overlap and eng.overlap_flushes > 0
    # conservation after the run: swap freed what it extracted
    assert eng.blocks.num_used == 0
    assert (eng.blocks.num_free + eng.blocks.num_cached
            + eng.blocks.num_hosted == eng.blocks.num_blocks - 1)
    # a 1-byte budget can never reserve a set: recompute fallback, exact
    starved, s_eng = _run_swap(model, params, trace, "always",
                               swap_bytes=1, **kw)
    assert starved == swp
    assert s_eng.stats().swap_outs == 0
    assert s_eng.stats().preemptions > 0


def test_swap_sampled_bitwise_and_auto_policy(gpt2_setup):
    """Sampled arm: seeded streams under swap preemption are BITWISE
    identical to the roomy-pool unpreempted run (swap keeps the
    request's emitted output intact, so fold indices never shift), and
    ``auto`` stays exact while actually exercising its estimate — on
    this geometry a victim's few KV blocks are far cheaper to move
    than the weight reads its re-prefill would stream, so auto picks
    the swap arm."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(9)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 14)
             for _ in range(4)]
    kws = [dict(temperature=0.9, top_k=20, top_p=0.9, seed=s)
           for s in (1, 2, 3)] + [dict()]
    base, _ = _run_swap(model, params, trace, "off", kws=kws,
                        num_slots=3, block_size=4, num_blocks=40,
                        prefill_chunk=8, max_model_len=32)
    tight, teng = _run_swap(model, params, trace, "always", kws=kws,
                            num_slots=3, block_size=4, num_blocks=9,
                            prefill_chunk=8, max_model_len=32)
    assert teng.stats().preemptions > 0 and teng.stats().swap_outs > 0
    assert tight == base                    # bitwise, greedy rider too
    auto, aeng = _run_swap(model, params, trace, "auto", kws=kws,
                           num_slots=3, block_size=4, num_blocks=9,
                           prefill_chunk=8, max_model_len=32)
    assert auto == base
    assert aeng.stats().swap_policy == "auto"
    # 2 * set_bytes << param_bytes * prefill_dispatches here: the
    # estimate picks swap, and the telemetry names the avoided work
    assert aeng.stats().swap_outs > 0
    assert aeng.stats().recompute_tokens_avoided > 0


def test_swap_preemption_exact_int8_pools(gpt2_setup):
    """int8 arm: the scale planes travel with the value blocks, so a
    swapped int8 request restores bitwise and stays token-exact vs
    generate_causal on the int8-cache config."""
    cfg, model, params = gpt2_setup
    int8 = _int8_model(model, cfg)
    rng = np.random.RandomState(12)
    trace = [(rng.randint(1, 120, (9,)).astype(np.int32), 18)
             for _ in range(4)]
    kw = dict(num_slots=4, block_size=4, num_blocks=10, prefill_chunk=8,
              max_model_len=32)
    swp, eng = _run_swap(int8, params, trace, "always", **kw)
    rec, _ = _run_swap(int8, params, trace, "never", **kw)
    assert swp == rec
    for (p, m), got in zip(trace, swp):
        assert got == _reference(int8, params, p, m, cfg.eos_token_id)
    assert eng.stats().swap_outs > 0
    assert eng.kv_cache_dtype == "int8"


def test_prefix_demotion_revives_instead_of_recomputing(gpt2_setup):
    """The demotion tier: two templates alternating over a pool that
    holds only one — evict-only (swap='off') pays a cold miss every
    swing, the tier ('never': demote active, recompute preemption)
    revives demoted blocks from host and keeps the hit rate up, tokens
    identical."""
    cfg, model, params = gpt2_setup
    rng = np.random.RandomState(31)
    t1 = rng.randint(1, 120, (16,)).astype(np.int32)
    t2 = rng.randint(1, 120, (16,)).astype(np.int32)
    trace = []
    for _ in range(6):
        for t in (t1, t2):
            tail = rng.randint(1, 120, (2,)).astype(np.int32)
            trace.append((np.concatenate([t, tail]), 3))
    kw = dict(num_slots=1, block_size=4, num_blocks=8, prefill_chunk=8,
              max_model_len=32)
    off, off_eng = _run_swap(model, params, trace, "off", **kw)
    tier, tier_eng = _run_swap(model, params, trace, "never", **kw)
    assert tier == off
    off_hit = off_eng.stats().cache_hit_rate or 0.0
    tier_hit = tier_eng.stats().cache_hit_rate or 0.0
    assert tier_hit > off_hit               # revives beat cold misses
    st = tier_eng.stats()
    assert st.host_tier_hits > 0
    assert st.host_tier_hit_rate and 0 < st.host_tier_hit_rate <= 1
    assert off_eng.stats().host_tier_hit_rate is None  # off: field absent
    # host-tier state drains clean: every hosted block still conserved
    bm = tier_eng.blocks
    assert (bm.num_free + bm.num_used + bm.num_cached + bm.num_hosted
            == bm.num_blocks - 1)


def test_parse_swap_knobs(monkeypatch):
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ENV_SWAP,
        ENV_SWAP_BYTES,
        parse_swap,
        parse_swap_bytes,
    )

    assert parse_swap(None) == "off"        # default: tier fully off
    for mode in ("auto", "always", "never", "off"):
        assert parse_swap(mode) == mode
    monkeypatch.setenv(ENV_SWAP, "always")
    assert parse_swap(None) == "always"
    with pytest.raises(ValueError, match=ENV_SWAP):
        parse_swap("sometimes")

    assert parse_swap_bytes(None) is None   # unbounded
    assert parse_swap_bytes(0) is None      # 0 = unbounded too
    assert parse_swap_bytes(1 << 20) == 1 << 20
    assert parse_swap_bytes("4096") == 4096
    monkeypatch.setenv(ENV_SWAP_BYTES, "2048")
    assert parse_swap_bytes(None) == 2048
    with pytest.raises(ValueError, match=ENV_SWAP_BYTES):
        parse_swap_bytes(-1)


def test_revive_survives_budget_eviction_during_reservation():
    """Regression (found by the bench's budgeted run): an admission
    that matched host-tier payloads must not lose them to its OWN
    allocations. ``_reserve``'s revive-block / private-block allocates
    can evict cached blocks, and spilling those under a FULL host
    budget evicts payloads oldest-first — which is exactly where the
    matched (still LRU-cold, peek mutates nothing) entries sit.
    Unpinned, ``revive_hosted`` KeyErrors; pinned, the in-flight
    demotions drop instead (a demoted prefix is an opportunity, a
    matched one a commitment) and the revival lands."""
    from types import SimpleNamespace

    bm = BlockManager(num_blocks=10, block_size=4)
    s = Scheduler(2, bm, 4, 16, prefix_cache=True)
    # budget = exactly two 64-byte payloads: demoting anything further
    # must evict oldest-first
    bm.set_spill(lambda b: SimpleNamespace(nbytes=64), host_budget=128)

    # park prefix A (2 full blocks) host-side ONLY: register, release,
    # demote both payloads (budget now full), then reclaim the demoted
    # device ids so a future match is host-tier-or-nothing
    tokens_a = np.arange(1, 9).astype(np.int32)
    ta = bm.allocate(2)
    bm.register_prefix(tokens_a, ta)
    bm.release(ta)
    assert bm.demote(max_blocks=2) == 2
    held = bm.allocate(7) + bm.allocate(2)   # 2nd call reclaims hosted
    assert bm.num_hosted == 0 and bm.num_free == 0
    # refill the LRU with OTHER registered prefixes (3 x 2 blocks) so
    # the admission below must evict-and-spill to allocate at all
    for lo in (20, 40, 60):
        t = held[:2]
        held = held[2:]
        bm.register_prefix(np.arange(lo, lo + 8).astype(np.int32), t)
        bm.release(t)
    assert bm.num_cached == 6 and bm.num_free == 0

    # admission: prompt = prefix A + one fresh block. peek_hosted
    # matches A's 2 keys; the 3 needed allocations each evict + spill
    # a cached block against the full budget
    s.submit(Request(prompt=np.concatenate(
        [tokens_a, np.arange(100, 104).astype(np.int32)]),
        max_new_tokens=2))
    [slot] = s.admit()
    assert bm.host_tier_hits == 2
    assert len(slot.pending_restores) == 2
    assert slot.prefill_pos == 8             # revived spans skipped
    # the matched payloads survived; the in-flight demotions were
    # dropped, not queued behind them
    assert bm.host_evictions == 0
    assert (bm.num_free + bm.num_used + bm.num_cached
            + bm.num_hosted == bm.num_blocks - 1)
