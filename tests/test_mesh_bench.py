"""Scaling-instrument tests: the --mesh bench's trace capture + XPlane
parsing must find real collective time on a dp8 mesh (VERDICT r1
next-steps #8 — the instrument for the ≥90% 8→32 scaling north star)."""

import numpy as np
import pytest

from benchmarks.mesh_bench import classify_event, profile_train_steps
from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer


def test_classify_event():
    assert classify_event("all-reduce.204") == "collective"
    assert classify_event("fusion.all-gather.3") == "collective"
    assert classify_event("collective-permute-start") == "collective"
    assert classify_event("dot.1") == "compute"
    assert classify_event("wrapped_reduce") == "compute"  # not a collective
    assert classify_event("ThreadpoolListener::Record") is None
    assert classify_event("$profiler.py:246 trace") is None
    assert classify_event("end: all-reduce") == "collective"  # negligible dur


def test_profile_breakdown_finds_collectives(devices8, tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    mesh = build_mesh(MeshConfig(), devices=devices8)  # dp8
    enc = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=32)
    model = BertForSequenceClassification(enc, num_labels=2)
    params = init_params(model, enc, seed=0)
    cfg = TrainConfig(dtype="float32", log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=512)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=32)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=False, seed=0)

    summary = profile_train_steps(trainer, batcher, steps=3,
                                  trace_dir=str(tmp_path))
    # dp8 gradient sync = a real all-reduce every step; device compute
    # must dominate but the collective share must be visible and sane
    assert summary["compute_ms"] > 0
    assert summary["collective_ms"] > 0
    assert 0 < summary["collective_fraction"] < 1
    assert any("all-reduce" in k for k in summary["top_collectives"])
    assert np.isfinite(summary["wall_step_ms"]) and summary["wall_step_ms"] > 0
