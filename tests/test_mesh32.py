"""32-virtual-device full-mesh correctness (VERDICT r1 next-steps #8).

Spawns a child with a forced 32-device CPU backend (the conftest pins
this process to 8, so the wider mesh needs its own process) and asserts
the dp4 x fsdp2 x tp2 x sp2 training-step loss sequence matches a
1-device run exactly — all four parallelism axes at once, the shape the
8→32-chip scaling story runs on real hardware.
"""

import os
import subprocess
import sys

from huggingface_sagemaker_tensorflow_distributed_tpu.launch.launcher import cpu_sim_env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_mesh32_full_axis_parity():
    child = os.path.join(_REPO, "tests", "_mesh32_child.py")
    env = cpu_sim_env(32)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, child], env=env, cwd=_REPO,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-4000:]
    assert "mesh32 ok" in proc.stdout
