"""Fleet-level distributed request tracing (ISSUE 19): cross-engine
stitching must reassemble a migrated request's whole history into ONE
causal trace whose hop-aware decomposition (router_queue + prefill +
transport + decode_admission + decode + preempted + overhead) telescopes
exactly to e2e, degrade torn/partial streams to FLAGGED-incomplete
traces (never wrong ones), roll stitched traces into byte-deterministic
fleet attribution (``obsctl trace|fleet``), and hold on a REAL forced
mid-decode migration — all on the stdlib-only side of the obs contract.
"""

import json
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.obs.trace import (
    TRACE_PHASES,
    check_trace,
    collect_traces,
    fleet_chrome_trace,
    fleet_summary,
    fleet_text,
    trace_text,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBSCTL = os.path.join(_REPO, "scripts", "obsctl.py")


# -- synthetic traced streams (pure host, no jax) -----------------------------

def _sub(tid, rid, t=1000.0, replica=0):
    return {"v": 1, "t": t, "host": 0, "pid": 1, "type": "serve",
            "event": "submit", "request": rid, "max_new_tokens": 10,
            "trace_id": tid, "hop": 0, "replica": replica}


def _mig(tid, rid, t=1000.35, hop=1, frm=0, to=1, extract=0.02,
         restore=0.01, hop_s=0.06, **extra):
    """One hot migrate event pricing the hop: transport_hop_s covers
    the hold segment (0.05) + restore (0.01) exactly by default."""
    ev = {"v": 1, "t": t, "host": 0, "pid": 1, "type": "serve",
          "event": "migrate", "request": rid, "from_replica": frm,
          "to_replica": to, "migration_bytes": 4096,
          "restore_s": restore, "extract_s": extract,
          "transport_hop_s": hop_s, "trace_id": tid, "hop": hop}
    ev.update(extra)
    return ev


def _tl(tid, rid, t=1000.8, at="finish", hop=1, group="", **over):
    """The finish timeline of a one-hop migrated request whose
    aggregates and segments agree by construction: queue 0.1 @r0,
    prefill 0.2 @r0, migration hold 0.05 @r1 (via=migrate, hop 1),
    decode 0.4 @r1, overhead 0.05 (of which 0.01 is the restore)."""
    ev = {"v": 1, "t": t, "host": 0, "pid": 1, "type": "serve",
          "event": "request_timeline", "request": rid, "at": at,
          "e2e_s": 0.8, "queue_s": 0.1, "prefill_s": 0.2,
          "decode_s": 0.4, "preempted_s": 0.05, "overhead_s": 0.05,
          "tokens": 10, "prompt_len": 5, "preemptions": 1,
          "ttft_s": 0.3, "trace_id": tid, "hop": hop, "replica": 1,
          "segments": [
              {"ph": "queue", "t0": 0.0, "dur": 0.1, "replica": 0},
              {"ph": "prefill", "t0": 0.1, "dur": 0.2, "from": 0,
               "chunks": 1, "replica": 0},
              {"ph": "preempted", "t0": 0.3, "dur": 0.05,
               "via": "migrate", "hop": 1, "replica": 1},
              {"ph": "decode", "t0": 0.36, "dur": 0.4, "bucket": 64,
               "iters": 10, "tokens": 10, "replica": 1},
          ]}
    if group:
        ev["group"] = group
    ev.update(over)
    return ev


def _one_hop(tid="t000000", rid=0, t=1000.0, group=""):
    return [_sub(tid, rid, t=t),
            _mig(tid, rid, t=t + 0.35),
            _tl(tid, rid, t=t + 0.8, group=group)]


def _write_events(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


# -- the stitch ----------------------------------------------------------------

def test_stitch_one_hop_complete_and_telescoped_phases():
    """The core contract: a migrated request's events — in ANY input
    order — stitch into one complete trace whose cross-hop phases
    telescope exactly to e2e and pass every consistency check."""
    events = _one_hop()
    shuffled = list(events)
    random.Random(7).shuffle(shuffled)
    for stream in (events, list(reversed(events)), shuffled):
        traces = collect_traces(stream)
        assert len(traces) == 1
        tr = traces[0]
        assert tr["complete"] and tr["incomplete"] == []
        assert tr["trace_id"] == "t000000" and tr["request"] == 0
        assert tr["hops"] == 1 and tr["replicas"] == [0, 1]
        assert tr["e2e_s"] == 0.8 and tr["ttft_s"] == 0.3
        # the telescoped decomposition: tagged hold seconds move into
        # transport/decode_admission, restore out of overhead
        assert tr["phases"] == {
            "router_queue": 0.1, "prefill": 0.2, "transport": 0.03,
            "decode_admission": 0.03, "decode": 0.4,
            "preempted": 0.0, "overhead": 0.04}
        assert sum(tr["phases"][ph] for ph in TRACE_PHASES) \
            == pytest.approx(0.8)
        assert check_trace(tr) == []


def test_stitch_keeps_router_scoped_ids_apart():
    """Trace ids are router-scoped sequences: the same id from two
    processes (two runs appended into one stream) must NOT merge."""
    a = _one_hop("t000000", rid=0)
    b = [dict(e, pid=2) for e in _one_hop("t000000", rid=5)]
    traces = collect_traces(a + b)
    assert len(traces) == 2
    assert sorted(t["request"] for t in traces) == [0, 5]
    assert all(t["complete"] for t in traces)


def test_stitch_degrades_torn_and_partial_streams_to_flagged():
    """Incompleteness is FLAGGED, never silently wrong: a torn tail
    (no timeline), a preempt-partial final timeline, a finish at a
    stale hop, and a hop with no migrate/requeue evidence each name
    their reason; check_trace treats flagged traces as non-errors."""
    sub, mig, tl = _one_hop()
    # torn tail: lifecycle events but the timeline never landed
    (tr,) = collect_traces([sub, mig])
    assert not tr["complete"]
    assert any("torn tail" in r for r in tr["incomplete"])
    assert check_trace(tr) == []
    # final timeline is a preempt-requeue partial, not a finish
    (tr,) = collect_traces([sub, mig, dict(tl, at="preempt")])
    assert not tr["complete"]
    assert any("not finish" in r for r in tr["incomplete"])
    # stale finish: hop-2 evidence exists but the finish is hop-1
    mig2 = _mig("t000000", 0, t=1000.5, hop=2, frm=1, to=0)
    (tr,) = collect_traces([sub, mig, mig2, tl])
    assert not tr["complete"]
    assert any("stale finish" in r for r in tr["incomplete"])
    # missing hop evidence: the finish claims hop 1 but no migrate or
    # requeue event ever recorded the move
    (tr,) = collect_traces([sub, tl])
    assert not tr["complete"]
    assert any("missing hop 1 evidence" in r for r in tr["incomplete"])
    # a trace spanning two request ids is flagged, not merged
    (tr,) = collect_traces([sub, mig, dict(tl, request=9)])
    assert any("request ids" in r for r in tr["incomplete"])
    # rendering an incomplete trace narrates the flags
    text = trace_text(tr)
    assert "INCOMPLETE" in text


def test_check_trace_names_gap_overlap_and_sum_bugs():
    """The consistency checks catch REAL accounting bugs: an inflated
    hop clock is an inter-hop gap, a deflated one an overlap, a
    priced hop without its hold segment is named, and a tampered
    aggregate fails both the five-way and telescoped sums."""
    sub, mig, tl = _one_hop()
    # inflated transport_hop_s: time lost between engines
    (tr,) = collect_traces([sub, dict(mig, transport_hop_s=0.2), tl])
    assert any("inter-hop gap" in e for e in check_trace(tr))
    # deflated: the hold segment claims more than the hop clock saw
    (tr,) = collect_traces([sub, dict(mig, transport_hop_s=0.01), tl])
    assert any("overlap" in e for e in check_trace(tr))
    # a priced hop whose migration hold never closed
    bad_tl = _tl("t000000", 0)
    bad_tl["segments"] = [s for s in bad_tl["segments"]
                          if s.get("via") != "migrate"]
    bad_tl["preempted_s"] = 0.0
    bad_tl["decode_s"] = 0.45    # keep the five-way sum consistent
    bad_tl["segments"][-1] = dict(bad_tl["segments"][-1], dur=0.45)
    (tr,) = collect_traces([sub, mig, bad_tl])
    assert any("no migration-hold segment" in e for e in check_trace(tr))
    # a tampered aggregate: the underlying five-way contract fires and
    # the telescoped sum breaks with it
    (tr,) = collect_traces([sub, mig, _tl("t000000", 0, decode_s=0.6)])
    errs = check_trace(tr)
    assert any("cross-hop phase sum" in e for e in errs)
    assert errs and check_trace(collect_traces([sub, mig, _tl(
        "t000000", 0)])[0]) == []


# -- fleet rollups -------------------------------------------------------------

def test_fleet_summary_counts_roles_replicas_and_tenants():
    events = (_one_hop("t000000", 0, t=1000.0, group="tenantA")
              + _one_hop("t000001", 1, t=1002.0, group="tenantB"))
    traces = collect_traces(events)
    s = fleet_summary(traces)
    assert (s["traces"], s["complete_traces"],
            s["trace_stitch_failures"]) == (2, 2, 0)
    assert s["phase_total_s"]["transport"] == pytest.approx(0.06)
    assert s["phase_frac"]["decode"] == pytest.approx(0.5)
    # fleet percentiles use the router's nearest-rank convention
    assert s["ttft_p50_s"] == 0.3 and s["ttft_p99_s"] == 0.3
    assert s["e2e_p50_s"] == 0.8
    assert s["transport_hops"] == 2 and s["migration_bytes"] == 8192
    assert s["transport_hop_s_p99"] == 0.06
    # roles are inferred from WHERE segments ran, no config needed
    assert s["per_role"]["prefill"]["replicas"] == [0]
    assert s["per_role"]["decode"]["replicas"] == [1]
    assert s["per_role"]["prefill"]["ttft_p50_s"] == 0.3
    assert "tpot_p50_s" in s["per_role"]["decode"]
    assert s["per_replica"]["0"]["prefill_s"] == pytest.approx(0.4)
    assert s["per_replica"]["1"]["decode_s"] == pytest.approx(0.8)
    assert s["per_replica"]["0"]["role"] == "prefill"
    assert set(s["per_group"]) == {"tenantA", "tenantB"}
    assert s["per_group"]["tenantA"]["traces"] == 1
    # an incomplete trace shifts the stitch counters, not the rollup
    s2 = fleet_summary(collect_traces(
        events + [_sub("t000002", 2, t=1004.0)]))
    assert s2["trace_stitch_failures"] == 1
    assert s2["incomplete"][0]["trace_id"] == "t000002"
    assert "stitch failure" in fleet_text(collect_traces(events))


def test_fleet_chrome_trace_multi_track_with_flow_arrows(tmp_path):
    """The merged export: one pid per REPLICA, and each hop drawn as
    an s->f flow pair crossing tracks at the right instants."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (
        validate_trace_file,
    )

    traces = collect_traces(_one_hop())
    doc = fleet_chrome_trace(traces)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["pid"] for e in xs] == [0, 0, 1, 1]   # segs on their replica
    assert all(e["tid"] == 0 for e in xs)
    flows = [e for e in doc["traceEvents"] if e["ph"] in ("s", "f")]
    assert [e["ph"] for e in flows] == ["s", "f"]
    assert all(e["id"] == "t000000/1" and e["cat"] == "transport"
               for e in flows)
    assert flows[0]["pid"] == 0 and flows[1]["pid"] == 1
    assert flows[1]["bp"] == "e"
    # the arrow spans source prefill end -> hold segment end
    assert flows[1]["ts"] - flows[0]["ts"] == pytest.approx(0.05e6)
    path = str(tmp_path / "fleet.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    n, errors = validate_trace_file(path)
    assert n == len(doc["traceEvents"]) and not errors


def test_chrome_timeline_per_replica_tracks():
    """Regression (ISSUE 19 satellite): ``obsctl timeline --trace``
    folded a whole router fleet — one OS process — onto one viewer
    track. Replica-tagged records now get their own stable pid;
    untagged single-engine exports keep pid 0."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        chrome_trace,
        collect_timelines,
    )

    recs = collect_timelines([
        _tl("", 0, replica=0, trace_id=None, hop=None),
        _tl("", 1, t=1001.0, replica=1, trace_id=None, hop=None),
    ])
    doc = chrome_trace(recs)
    pids = {e["args"]["request"]: e["pid"] for e in doc["traceEvents"]}
    assert pids[0] != pids[1]
    # untagged records keep the single-track projection
    untagged = collect_timelines([
        _tl("", 0, replica=None, trace_id=None, hop=None),
        _tl("", 1, t=1001.0, replica=None, trace_id=None, hop=None),
    ])
    assert {e["pid"] for e in chrome_trace(untagged)["traceEvents"]} \
        == {0}


# -- schema: mistyped trace context is rejected, not silently consumed --------

def test_schema_rejects_mistyped_trace_context_fields():
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (
        validate_event,
    )

    good = _mig("t000000", 0)
    assert validate_event(good) == []
    for field, bad in (("trace_id", 7), ("hop", "one"),
                       ("hop", True), ("replica", "0"),
                       ("transport_hop_s", "fast"),
                       ("extract_s", [0.02])):
        errs = validate_event(dict(good, **{field: bad}))
        assert errs and any(field in e for e in errs), (field, bad)
    stitch = {"v": 1, "t": 1000.0, "host": 0, "pid": 1,
              "type": "serve", "event": "trace_stitch", "traces": 8,
              "complete_traces": 8, "trace_stitch_failures": 0,
              "transport_hop_s_p99": 0.004}
    assert validate_event(stitch) == []
    assert validate_event(dict(stitch, trace_stitch_failures="0"))
    assert validate_event(dict(stitch, complete_traces=7.5))


# -- the real thing: forced mid-decode migration ------------------------------

@pytest.fixture(scope="module")
def gpt2_setup():
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (
        init_params,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )

    cfg = Gpt2Config(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=128, hidden_dropout=0.0,
                     embd_dropout=0.0, attention_dropout=0.0,
                     eos_token_id=127, pad_token_id=0, dtype=jnp.float32)
    model = Gpt2LMHeadModel(cfg)
    return cfg, model, init_params(model, cfg, seed=0)


def test_engine_mid_decode_migration_stitches_complete(gpt2_setup,
                                                       tmp_path):
    """End to end on real engines: a request migrated MID-DECODE
    leaves a stream that stitches into one complete hop-1 trace whose
    cross-hop decomposition passes every check, with the transport
    phase priced (> 0) and the hot migrate event carrying the hop
    clock. Tokens stay exact under tracing (the PR 18 contract)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.transport import (
        migrate_request,
    )

    _cfg, model, params = gpt2_setup
    kw = dict(num_slots=2, block_size=4, num_blocks=40,
              prefill_chunk=8, max_model_len=64,
              gather_buckets=[16, 32], timeline="on")
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 120, (9,)).astype(np.int32)

    base_eng = ServeEngine(model, params, **kw)
    base_req = base_eng.submit(prompt, 10)
    base_eng.run()
    base = list(base_eng.output_ids(base_req))

    out = tmp_path / "mid_decode"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        src = ServeEngine(model, params, **kw)
        dst = ServeEngine(model, params, **kw)
        src.replica, dst.replica = 0, 1
        req = src.submit(prompt, 10, trace_id="t000000")
        while src.has_work() and len(req.output) < 4:
            src.step()
        assert len(req.output) >= 1                  # mid-decode
        assert migrate_request(src, dst, req.rid) is not None
        assert req.hop == 1
        dst.run()
        obs.flush()
    finally:
        obs.reset()
    assert list(dst.output_ids(req)) == base

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        load_events,
    )
    events, errors = load_events([str(out)])
    assert not errors
    traces = collect_traces(events)
    assert len(traces) == 1
    tr = traces[0]
    assert tr["complete"], tr["incomplete"]
    assert tr["hops"] == 1 and tr["replicas"] == [0, 1]
    assert check_trace(tr) == []
    assert tr["phases"]["transport"] > 0
    (mig,) = tr["migrates"]
    assert mig["transport_hop_s"] >= mig["extract_s"] >= 0
    assert mig["from_replica"] == 0 and mig["to_replica"] == 1
    # the stitched ttft matches the engine's own stamp to the rounding
    assert tr["ttft_s"] == pytest.approx(req.ttft_s, abs=1e-6)


def test_engine_untraced_stream_carries_no_trace_fields(gpt2_setup,
                                                        tmp_path):
    """The absent-when-default contract: without a trace_id, no event
    gains trace_id/hop — the stream stays byte-compatible with the
    pre-tracing schema and the stitcher finds nothing to stitch."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs
    from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
        ServeEngine,
    )

    _cfg, model, params = gpt2_setup
    out = tmp_path / "untraced"
    obs.reset(out_dir=str(out), enabled=True)
    try:
        eng = ServeEngine(model, params, num_slots=2, block_size=4,
                          num_blocks=40, prefill_chunk=8,
                          max_model_len=64, gather_buckets=[16, 32],
                          timeline="on")
        eng.submit(np.arange(1, 9, dtype=np.int32), 4)
        eng.run()
        obs.flush()
    finally:
        obs.reset()
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.timeline import (
        load_events,
    )
    events, errors = load_events([str(out)])
    assert not errors and events
    assert all("trace_id" not in e and "hop" not in e for e in events)
    assert collect_traces(events) == []


# -- the CLI: byte-deterministic trace/fleet ----------------------------------

def _run_obsctl(*argv):
    return subprocess.run([sys.executable, _OBSCTL, *argv],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True, cwd=_REPO)


@pytest.fixture()
def stitched_dirs(tmp_path):
    """One traced run split across two event files the way a fleet
    writes them — the stitch must not care which file holds what."""
    a = tmp_path / "hostA"
    b = tmp_path / "hostB"
    sub, mig, tl = _one_hop("t000000", 0, group="tenantA")
    sub2, mig2, tl2 = _one_hop("t000001", 1, t=1002.0)
    _write_events(str(a / "events.jsonl"), [sub, mig, sub2])
    _write_events(str(b / "events.jsonl"), [tl, mig2, tl2])
    return [str(a), str(b)]


def test_cli_trace_narrative_and_determinism(stitched_dirs):
    proc = _run_obsctl("trace", "t000000", *stitched_dirs)
    assert proc.returncode == 0, proc.stderr
    assert "trace t000000" in proc.stdout
    assert "cross-hop decomposition" in proc.stdout
    assert "transport" in proc.stdout and "[migration hold]" in proc.stdout
    rev = _run_obsctl("trace", "t000000", *reversed(stitched_dirs))
    assert rev.returncode == 0 and rev.stdout == proc.stdout
    # selection by request id renders the same trace
    by_rid = _run_obsctl("trace", "0", *stitched_dirs)
    assert by_rid.returncode == 0 and by_rid.stdout == proc.stdout
    # unknown id: loud rc 1 with the known ids named
    missing = _run_obsctl("trace", "t999999", *stitched_dirs)
    assert missing.returncode == 1 and "t000000" in missing.stderr


def test_cli_trace_flags_incomplete_with_rc1(tmp_path):
    d = tmp_path / "torn"
    sub, mig, _tl_ = _one_hop()
    _write_events(str(d / "events.jsonl"), [sub, mig])   # torn tail
    proc = _run_obsctl("trace", "t000000", str(d))
    assert proc.returncode == 1
    assert "INCOMPLETE" in proc.stdout and "torn tail" in proc.stdout


def test_cli_fleet_table_json_trace_and_determinism(stitched_dirs,
                                                    tmp_path):
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.schema import (
        validate_trace_file,
    )

    proc = _run_obsctl("fleet", *stitched_dirs)
    assert proc.returncode == 0, proc.stderr
    assert "2 trace(s), 2 complete" in proc.stdout
    assert "role prefill" in proc.stdout and "tenantA" in proc.stdout
    rev = _run_obsctl("fleet", *reversed(stitched_dirs))
    assert rev.returncode == 0 and rev.stdout == proc.stdout
    js = _run_obsctl("fleet", "--json", *stitched_dirs)
    doc = json.loads(js.stdout)
    assert doc["complete_traces"] == 2
    assert doc["per_role"]["prefill"]["ttft_p50_s"] == 0.3
    # the merged chrome export is byte-identical under input order too
    t1, t2 = str(tmp_path / "f1.json"), str(tmp_path / "f2.json")
    assert _run_obsctl("fleet", *stitched_dirs,
                       "--trace", t1).returncode == 0
    assert _run_obsctl("fleet", *reversed(stitched_dirs),
                       "--trace", t2).returncode == 0
    with open(t1, "rb") as f1, open(t2, "rb") as f2:
        assert f1.read() == f2.read()
    n, errors = validate_trace_file(t1)
    assert n > 0 and not errors


def test_cli_fleet_rejects_malformed_and_inconsistent_input(tmp_path):
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "events.jsonl").write_text(
        '{"torn json\n' + json.dumps(_sub("t000000", 0)) + "\n")
    proc = _run_obsctl("fleet", str(bad))
    assert proc.returncode == 1 and "unparseable" in proc.stderr
    # a claimed-complete trace with broken accounting exits 1
    sick = tmp_path / "sick"
    sub, mig, _tl_ = _one_hop()
    _write_events(str(sick / "events.jsonl"),
                  [sub, dict(mig, transport_hop_s=0.5),
                   _tl("t000000", 0)])
    proc = _run_obsctl("fleet", str(sick))
    assert proc.returncode == 1 and "inter-hop gap" in proc.stderr
    # no traced events at all: named, rc 1
    empty = tmp_path / "empty"
    _write_events(str(empty / "events.jsonl"),
                  [dict(_sub("", 0), trace_id=None, hop=None,
                        replica=None)])
    proc = _run_obsctl("fleet", str(empty))
    assert proc.returncode == 1 and "no traced serve events" in proc.stderr
