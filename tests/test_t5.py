"""T5 encoder-decoder: forward numerics vs HF torch, HF conversion both
directions, cached greedy decode parity with teacher forcing, seq2seq
training end-to-end (SURVEY.md §7 stage 8 — the hardest model family:
relative-position buckets, tied embeddings, encoder-decoder attention)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models import generate as gen
from huggingface_sagemaker_tensorflow_distributed_tpu.models import t5 as t5_mod
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models

TINY = t5_mod.T5Config(
    vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
    num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
    relative_attention_max_distance=20, dropout_rate=0.0)


def _tiny_model(cfg=TINY, seed=0):
    model = t5_mod.T5ForConditionalGeneration(cfg)
    params = auto_models.init_params(model, cfg, seed=seed)
    return model, params


def _batch(cfg, batch=2, src=10, tgt=6, seed=0):
    r = np.random.RandomState(seed)
    src_ids = r.randint(2, cfg.vocab_size, (batch, src)).astype(np.int32)
    src_mask = np.ones((batch, src), np.int32)
    src_mask[1, 7:] = 0
    src_ids[1, 7:] = cfg.pad_token_id
    tgt_ids = r.randint(2, cfg.vocab_size, (batch, tgt)).astype(np.int32)
    return src_ids, src_mask, tgt_ids


def test_forward_shapes_finite():
    model, params = _tiny_model()
    src, mask, tgt = _batch(TINY)
    logits = model.apply({"params": params}, src, mask, tgt)
    assert logits.shape == (2, 6, TINY.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_relative_position_bucket_matches_hf_semantics():
    rp = jnp.arange(-12, 13)
    b_bi = t5_mod.relative_position_bucket(rp, True, 8, 20)
    b_causal = t5_mod.relative_position_bucket(rp, False, 8, 20)
    assert b_bi.min() >= 0 and b_bi.max() < 8
    assert b_causal.min() >= 0 and b_causal.max() < 8
    # causal: all future positions (rp > 0) collapse to bucket 0
    assert np.all(np.asarray(b_causal)[13:] == 0)
    # bidirectional: sign split at num_buckets // 2
    assert np.asarray(b_bi)[-1] >= 4


def test_cached_decode_matches_teacher_forcing():
    """Greedy decode with the KV cache must equal argmax over full
    (uncached) decoder forwards step by step."""
    model, params = _tiny_model(seed=1)
    src, mask, _ = _batch(TINY, seed=1)
    T = 5
    out_cached = np.asarray(gen.generate(model, params, src, mask,
                                         max_new_tokens=T))
    # uncached reference: grow decoder_input_ids, full forward each step
    enc = model.apply({"params": params}, src, mask, deterministic=True,
                      method=model.encode)
    dec_in = np.full((2, 1), TINY.decoder_start_token_id, np.int32)
    finished = np.zeros(2, bool)
    ref_tokens = []
    for _ in range(T):
        logits = model.apply({"params": params}, dec_in, enc, mask,
                             deterministic=True, method=model.decode)
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        nxt = np.where(finished, TINY.pad_token_id, nxt)
        finished |= nxt == TINY.eos_token_id
        ref_tokens.append(nxt)
        dec_in = np.concatenate([dec_in, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out_cached, np.stack(ref_tokens, 1))


def test_from_seq2seq_targets_are_lm_style():
    """Targets = raw tokens + model EOS (no CLS/SEP): the decoder learns
    to emit exactly what generate() stops on."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset, WordHashTokenizer)
    tok = WordHashTokenizer(vocab_size=256)
    ds = ArrayDataset.from_seq2seq(tok, ["a b c"], ["x y"],
                                   max_source_length=8, max_target_length=6,
                                   decoder_start_token_id=0, pad_token_id=0,
                                   eos_token_id=1)
    labels = ds.columns["labels"][0]
    dec_in = ds.columns["decoder_input_ids"][0]
    # two target tokens then EOS, rest ignore-index
    assert labels[2] == 1 and (labels[3:] == -100).all()
    assert labels[0] not in (tok.cls_token_id, tok.sep_token_id) or labels[0] > 3
    np.testing.assert_array_equal(dec_in[:4], [0, labels[0], labels[1], 1])


def test_shift_right():
    labels = jnp.asarray([[5, 6, 7, -100, -100]])
    out = t5_mod.shift_right(labels, decoder_start_token_id=0, pad_token_id=0)
    np.testing.assert_array_equal(np.asarray(out), [[0, 5, 6, 7, 0]])


def test_seq2seq_training_learns():
    """End-to-end: tiny T5 on synthetic summarization, loss must drop."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset, ShardedBatcher, WordHashTokenizer)
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_summarization)
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig, build_mesh)
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    cfg = t5_mod.T5Config(
        vocab_size=512, d_model=32, d_kv=8, d_ff=64, num_layers=1,
        num_decoder_layers=1, num_heads=4, dropout_rate=0.0)
    model, params = _tiny_model(cfg)
    tok = WordHashTokenizer(vocab_size=512)
    docs, sums = synthetic_summarization(64, seed=0, doc_len=(20, 40))
    ds = ArrayDataset.from_seq2seq(tok, docs, sums, max_source_length=48,
                                   max_target_length=8)
    mesh = build_mesh(MeshConfig(dp=-1))
    tconf = TrainConfig(task="seq2seq", dtype="float32", epochs=4,
                        train_batch_size=2, learning_rate=3e-3,
                        log_every_steps=0)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    trainer = Trainer(tconf, model, params, mesh)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.8


# --- HF parity -------------------------------------------------------------

torch = pytest.importorskip("torch")
import transformers  # noqa: E402


@pytest.fixture(scope="module")
def hf_t5_dir(tmp_path_factory):
    torch.manual_seed(7)
    cfg = transformers.T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        decoder_start_token_id=0)
    d = str(tmp_path_factory.mktemp("t5"))
    m = transformers.T5ForConditionalGeneration(cfg).eval()
    m.save_pretrained(d)
    return d, m


def test_t5_parity_vs_hf(hf_t5_dir):
    d, m = hf_t5_dir
    model, params, family, cfg = auto_models.from_pretrained(d, task="seq2seq")
    assert family == "t5"
    src, mask, tgt = _batch(cfg, seed=2)
    with torch.no_grad():
        t_logits = m(input_ids=torch.tensor(src.astype(np.int64)),
                     attention_mask=torch.tensor(mask.astype(np.int64)),
                     decoder_input_ids=torch.tensor(tgt.astype(np.int64))
                     ).logits.numpy()
    j_logits = np.asarray(model.apply({"params": params}, src, mask, tgt))
    np.testing.assert_allclose(j_logits, t_logits, atol=2e-4, rtol=1e-3)


def test_t5_export_roundtrip_loads_in_hf(hf_t5_dir, tmp_path):
    d, m = hf_t5_dir
    model, params, family, cfg = auto_models.from_pretrained(d, task="seq2seq")
    out_dir = str(tmp_path / "export")
    auto_models.save_pretrained(out_dir, params, family, cfg)
    reloaded = transformers.T5ForConditionalGeneration.from_pretrained(out_dir).eval()
    src, mask, tgt = _batch(cfg, seed=3)
    with torch.no_grad():
        a = m(input_ids=torch.tensor(src.astype(np.int64)),
              attention_mask=torch.tensor(mask.astype(np.int64)),
              decoder_input_ids=torch.tensor(tgt.astype(np.int64))).logits
        b = reloaded(input_ids=torch.tensor(src.astype(np.int64)),
                     attention_mask=torch.tensor(mask.astype(np.int64)),
                     decoder_input_ids=torch.tensor(tgt.astype(np.int64))).logits
    np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)


def test_t5_greedy_generate_matches_hf(hf_t5_dir):
    d, m = hf_t5_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="seq2seq")
    src, mask, _ = _batch(cfg, seed=4)
    ours = np.asarray(gen.generate(model, params, src, mask, max_new_tokens=6))
    with torch.no_grad():
        theirs = m.generate(input_ids=torch.tensor(src.astype(np.int64)),
                            attention_mask=torch.tensor(mask.astype(np.int64)),
                            max_new_tokens=6, do_sample=False,
                            num_beams=1).numpy()
    # HF prepends decoder_start and may stop early at EOS; compare the
    # generated prefix token-for-token.
    for b in range(src.shape[0]):
        hf_seq = theirs[b][1:]  # drop decoder_start
        n = min(len(hf_seq), ours.shape[1])
        np.testing.assert_array_equal(ours[b, :n], hf_seq[:n])


def test_beam1_score_dominates_greedy():
    model, params = _tiny_model(seed=3)
    src, mask, _ = _batch(TINY, seed=3)
    greedy = np.asarray(gen.generate(model, params, src, mask, max_new_tokens=6))
    # greedy's exact path is in beam-1's search space: at length_penalty
    # 0 the pooled winner's raw sum-log-prob must be at least greedy's
    _, s1 = gen.beam_search_generate(model, params, src, mask, num_beams=1,
                                     max_new_tokens=6, length_penalty=0.0,
                                     return_scores=True)
    logp = _sequence_logprob(model, params, src, mask, greedy)
    assert np.all(np.asarray(s1) >= logp - 1e-4)


def _sequence_logprob(model, params, src, mask, out_tokens):
    """Teacher-forced raw sum log-prob of generated tokens up to and
    including EOS (the quantity beam search maximizes at penalty 0)."""
    import jax.numpy as jnp

    B, T = out_tokens.shape
    dec_in = np.concatenate(
        [np.full((B, 1), TINY.decoder_start_token_id, np.int32),
         out_tokens[:, :-1]], axis=1)
    logits = model.apply({"params": params}, src, mask,
                         jnp.asarray(dec_in), deterministic=True)
    logp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32)))
    tok_lp = np.take_along_axis(logp, out_tokens[:, :, None], axis=-1)[..., 0]
    total = np.zeros(B)
    for b in range(B):
        for t in range(T):
            total[b] += tok_lp[b, t]
            if out_tokens[b, t] == TINY.eos_token_id:
                break
    return total


def test_beam_search_pads_after_eos():
    model, params = _tiny_model(seed=5)
    src, mask, _ = _batch(TINY, seed=5)
    out = np.asarray(gen.beam_search_generate(model, params, src, mask,
                                              num_beams=3, max_new_tokens=8))
    assert out.shape == (2, 8)
    for row in out:
        if TINY.eos_token_id in row:
            after = row[list(row).index(TINY.eos_token_id) + 1:]
            assert np.all(after == TINY.pad_token_id)


@pytest.mark.parametrize("num_beams,length_penalty,seed",
                         [(4, 1.0, 6), (2, 0.0, 7), (4, 2.0, 8), (3, 1.0, 9)])
def test_t5_beam_search_matches_hf(hf_t5_dir, num_beams, length_penalty, seed):
    """Beam decode vs HF transformers on the same weights: same
    algorithm (2K candidates, finished-hypothesis pool with add-time
    length penalty, is_done early-stop bookkeeping), so outputs must
    agree token-for-token across beam widths and penalties."""
    d, m = hf_t5_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="seq2seq")
    src, mask, _ = _batch(cfg, seed=seed)
    ours = np.asarray(gen.beam_search_generate(
        model, params, src, mask, num_beams=num_beams, max_new_tokens=6,
        length_penalty=length_penalty))
    with torch.no_grad():
        theirs = m.generate(input_ids=torch.tensor(src.astype(np.int64)),
                            attention_mask=torch.tensor(mask.astype(np.int64)),
                            max_new_tokens=6, do_sample=False,
                            num_beams=num_beams,
                            length_penalty=length_penalty,
                            early_stopping=False).numpy()
    for b in range(src.shape[0]):
        hf_seq = theirs[b][1:]  # drop decoder_start
        n = min(len(hf_seq), ours.shape[1])
        np.testing.assert_array_equal(ours[b][:n], hf_seq[:n])


def test_sampling_filters():
    """top_k / top_p logit filters: exact mask semantics on a known
    distribution (HF TopK/TopPLogitsWarper parity)."""
    import jax.numpy as jnp
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
        _filter_top_k,
        _filter_top_p,
    )

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    k2 = np.asarray(_filter_top_k(logits, 2))
    assert np.isfinite(k2[0, :2]).all() and np.isinf(k2[0, 2:]).all()
    # top_p=0.8: 0.5 (cum-before 0) + 0.3 (cum-before 0.5) kept, 0.15
    # (cum-before 0.8, not < 0.8) dropped
    p8 = np.asarray(_filter_top_p(logits, 0.8))
    assert np.isfinite(p8[0, :2]).all() and np.isinf(p8[0, 2:]).all()
    # top_p=0.81 keeps the third token (cum-before 0.8 < 0.81)
    p81 = np.asarray(_filter_top_p(logits, 0.81))
    assert np.isfinite(p81[0, :3]).all() and np.isinf(p81[0, 3:]).all()


def test_sampled_generation_respects_top_k():
    """With top_k=1, sampling at any temperature degenerates to greedy."""
    model, params = _tiny_model(seed=6)
    src, mask, _ = _batch(TINY, seed=6)
    greedy = np.asarray(gen.generate(model, params, src, mask, max_new_tokens=5))
    topk1 = np.asarray(gen.generate(model, params, src, mask, max_new_tokens=5,
                                    temperature=1.7, top_k=1, seed=9))
    np.testing.assert_array_equal(topk1, greedy)
