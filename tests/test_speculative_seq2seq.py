"""Seq2seq speculative decoding (generate_speculative_seq2seq, T5).

Contract: temperature 0 output is EXACTLY ``generate``'s greedy
continuation for every draft and acceptance pattern (the draft encodes
the source with its OWN encoder and proposes decoder tokens; the
target verifies each window in one decoder pass; per-row cache-index
rewinds keep batched rows independent — T5's relative-position bias
follows the per-row indices). BART is rejected loudly: its absolute
decoder positions live in a shared scalar that per-row rewinds would
corrupt.
"""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    generate,
    generate_speculative_seq2seq,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
)


def _t5(num_layers, seed):
    cfg = T5Config(vocab_size=96, d_model=32, d_kv=8, d_ff=64,
                   num_layers=num_layers, num_decoder_layers=num_layers,
                   num_heads=4, dropout_rate=0.0)
    model = T5ForConditionalGeneration(cfg)
    return model, init_params(model, cfg, seed=seed)


@pytest.mark.parametrize("k", [1, 3, 5])
def test_t5_speculative_matches_greedy(k):
    target, t_params = _t5(2, seed=0)
    draft, d_params = _t5(1, seed=1)
    rng = np.random.RandomState(0)
    src = rng.randint(3, 96, (2, 8))
    mask = np.ones((2, 8), np.int64)
    mask[1, 6:] = 0                        # padded source row
    want = np.asarray(generate(target, t_params, src, mask,
                               max_new_tokens=12))
    got = np.asarray(generate_speculative_seq2seq(
        target, t_params, draft, d_params, src, mask, max_new_tokens=12,
        speculate_k=k))
    np.testing.assert_array_equal(got, want)


def test_t5_speculative_perfect_draft():
    target, t_params = _t5(2, seed=0)
    rng = np.random.RandomState(1)
    src = rng.randint(3, 96, (2, 6))
    want = np.asarray(generate(target, t_params, src, max_new_tokens=10))
    got, stats = generate_speculative_seq2seq(
        target, t_params, target, t_params, src, max_new_tokens=10,
        speculate_k=4, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["window_ceiling"] == 5
    if not (want == 1).any():              # no EOS: full acceptance
        assert stats["accepted_per_window"] == 5.0


def test_t5_speculative_sampled_deterministic():
    target, t_params = _t5(2, seed=0)
    draft, d_params = _t5(1, seed=1)
    src = np.random.RandomState(2).randint(3, 96, (1, 6))
    a = np.asarray(generate_speculative_seq2seq(
        target, t_params, draft, d_params, src, max_new_tokens=10,
        speculate_k=3, temperature=0.8, seed=5))
    b = np.asarray(generate_speculative_seq2seq(
        target, t_params, draft, d_params, src, max_new_tokens=10,
        speculate_k=3, temperature=0.8, seed=5))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < 96).all()


def test_bart_rejected():
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartConfig,
        BartForConditionalGeneration,
    )

    cfg = BartConfig(vocab_size=64, d_model=32, encoder_layers=1,
                     decoder_layers=1, encoder_attention_heads=4,
                     decoder_attention_heads=4, encoder_ffn_dim=64,
                     decoder_ffn_dim=64, max_position_embeddings=64,
                     dropout=0.0, attention_dropout=0.0)
    model = BartForConditionalGeneration(cfg)
    params = init_params(model, cfg)
    t5, t5_params = _t5(1, seed=0)
    with pytest.raises(ValueError, match="T5 family"):
        generate_speculative_seq2seq(model, params, t5, t5_params,
                                     np.ones((1, 4), np.int64))
