"""Masked-LM task tests: HF torch parity for the tied-decoder heads
(BERT/RoBERTa/DistilBERT), whole-word masking statistics, and the mlm
training path end to end (the pretraining recipe behind the reference's
default checkpoint bert-large-uncased-whole-word-masking)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (  # noqa: E402
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (  # noqa: E402
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer  # noqa: E402

TOL = 2e-4


def _inputs(vocab, batch=3, seq=12, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(4, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    return ids, mask


@pytest.mark.parametrize("family", ["bert", "roberta", "distilbert"])
def test_mlm_head_parity(family, tmp_path):
    torch.manual_seed(0)
    if family == "bert":
        cfg = transformers.BertConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0)
        m = transformers.BertForMaskedLM(cfg).eval()
    elif family == "roberta":
        cfg = transformers.RobertaConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=66, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, pad_token_id=1)
        m = transformers.RobertaForMaskedLM(cfg).eval()
    else:
        cfg = transformers.DistilBertConfig(
            vocab_size=128, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
            max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)
        m = transformers.DistilBertForMaskedLM(cfg).eval()
    # perturb EVERY param away from init (LN gammas included) so a
    # conversion rule that silently drops a weight cannot hide behind
    # fresh-init defaults (ones/zeros)
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / family)
    m.save_pretrained(d)

    model, params, fam, _ = auto_models.from_pretrained(d, task="mlm")
    assert fam == family
    ids, mask = _inputs(128)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


def test_whole_word_masking_statistics():
    tok = WordHashTokenizer(vocab_size=512)
    texts = ["the quick brown fox jumps over the lazy dog " * 4] * 50
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=48, seed=0)
    ids = ds.columns["input_ids"]
    labels = ds.columns["labels"]
    am = ds.columns["attention_mask"]
    masked = labels != -100
    # ~15% of real tokens predicted (CLS/SEP excluded)
    frac = masked.sum() / (am.sum() - 2 * len(texts))
    assert 0.08 < frac < 0.25
    # of the predicted positions, ~80% are the mask id
    mask_frac = (ids[masked] == tok.mask_token_id).mean()
    assert 0.6 < mask_frac < 0.95
    # unmasked positions keep their ids and are ignored by the loss
    assert np.all(labels[~masked] == -100)
    # whole-word: every repetition of a chosen word is independent, but
    # within one row a chosen word's token IS its whole word here (the
    # hash tokenizer is one-token-per-word), so just verify every masked
    # label was a real token
    assert np.all(labels[masked] >= 0)


def test_whole_word_masks_all_subwords(tmp_path):
    """With a real subword tokenizer, every subword of a chosen word is
    predicted together (the WWM property)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.wordpiece import (
        WordPieceTokenizer,
    )

    vocab = {w: i for i, w in enumerate(
        ["[PAD]", "[CLS]", "[SEP]", "[UNK]", "[MASK]",
         "play", "##ing", "##ground", "the", "on"])}
    tok = WordPieceTokenizer(vocab)
    texts = ["playing on the playground"] * 30
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=12, seed=1)
    labels = ds.columns["labels"]
    enc = tok.encode_words([["playing", "on", "the", "playground"]] * 30,
                           max_length=12)
    wid = enc["word_ids"]
    for r in range(len(texts)):
        # for every word, its subword positions are either all predicted
        # or none
        for w in range(wid[r].max() + 1):
            pos = wid[r] == w
            flags = labels[r][pos] != -100
            assert flags.all() or not flags.any()


def test_mlm_with_hf_byte_bpe_tokenizer(tmp_path):
    """RoBERTa-style byte-BPE fast tokenizer through from_mlm_texts:
    must tokenize RAW text (pre-split input would be rejected without
    add_prefix_space and would change the ids) and mask whole words."""
    from tokenizers import ByteLevelBPETokenizer

    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        HFTokenizer,
    )

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog\n" * 50)
    bpe = ByteLevelBPETokenizer()
    bpe.train([str(corpus)], vocab_size=300, min_frequency=1,
              special_tokens=["<s>", "<pad>", "</s>", "<unk>", "<mask>"])
    bpe.save_model(str(tmp_path))
    hf = transformers.RobertaTokenizerFast(
        vocab_file=str(tmp_path / "vocab.json"),
        merges_file=str(tmp_path / "merges.txt"),
        model_max_length=32)
    tok = HFTokenizer(hf)
    assert tok.mask_token_id is not None

    texts = ["the quick brown fox jumps over the lazy dog"] * 20
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=32, seed=0)
    labels = ds.columns["labels"]
    masked = labels != -100
    assert masked.any()
    # masked labels are real token ids from the natural tokenization
    nat = hf(texts[0], return_tensors="np")["input_ids"][0]
    assert set(labels[masked].tolist()) <= set(nat.tolist())


def test_mlm_masks_redrawn_per_epoch():
    """Dynamic masking: epoch 0 and epoch 1 draw different masks over the
    same clean tokens (HF collator diversity at epoch granularity), the
    redraw is deterministic (same epoch → same masks, the property
    mid-epoch resume and multi-host agreement both rely on), and the
    clean corpus is recoverable at every epoch."""
    tok = WordHashTokenizer(vocab_size=512)
    texts = ["the quick brown fox jumps over the lazy dog " * 4] * 40
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=48, seed=3)
    ids0 = ds.columns["input_ids"].copy()
    labels0 = ds.columns["labels"].copy()
    ds.begin_epoch(1)
    ids1 = ds.columns["input_ids"].copy()
    labels1 = ds.columns["labels"].copy()
    assert (labels0 != labels1).any(), "epoch 1 drew identical masks"
    # statistics hold at every epoch, not just build time
    am = ds.columns["attention_mask"]
    frac = (labels1 != -100).sum() / (am.sum() - 2 * len(texts))
    assert 0.08 < frac < 0.25
    # determinism: replaying epoch 0 reproduces the build-time masks
    ds.begin_epoch(0)
    np.testing.assert_array_equal(ds.columns["input_ids"], ids0)
    np.testing.assert_array_equal(ds.columns["labels"], labels0)
    # unmasked positions always carry the clean ids: reconstruct epoch-1
    # clean tokens from labels∪ids and compare with epoch-0's
    clean1 = np.where(labels1 != -100, labels1, ids1)
    clean0 = np.where(labels0 != -100, labels0, ids0)
    np.testing.assert_array_equal(clean1, clean0)


def test_mlm_batcher_drives_epoch_masking(devices8):
    """ShardedBatcher.local_batches(epoch) re-masks through begin_epoch:
    the same dataset row differs between epoch-0 and epoch-1 batches."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(32, seed=1)
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=16, seed=0)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    batcher = ShardedBatcher(ds, 32, mesh, shuffle=False, seed=0)
    b0 = next(iter(batcher.local_batches(epoch=0)))
    b1 = next(iter(batcher.local_batches(epoch=1)))
    assert (b0["labels"] != b1["labels"]).any()
    # attention mask (true lengths) never changes with the redraw
    np.testing.assert_array_equal(b0["attention_mask"], b1["attention_mask"])


def test_mlm_training_learns(devices8):
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_mlm_texts(tok, texts, max_length=16, seed=0)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForMaskedLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig

    model_cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                              num_heads=4, intermediate_size=64,
                              max_position_embeddings=16, hidden_dropout=0.0,
                              attention_dropout=0.0, use_pooler=False)
    model = BertForMaskedLM(model_cfg)
    params = init_params(model, model_cfg)
    cfg = TrainConfig(task="mlm", dtype="float32", learning_rate=5e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=3)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.9


def test_mlm_export_reloads_in_hf(tmp_path):
    """Our MLM export loads back into HF torch with identical logits
    (tied decoder reconstructed by HF's tie_weights)."""
    torch.manual_seed(0)
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    m = transformers.BertForMaskedLM(cfg).eval()
    d = str(tmp_path / "src")
    m.save_pretrained(d)
    model, params, fam, our_cfg = auto_models.from_pretrained(d, task="mlm")
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, fam, our_cfg)
    m2 = transformers.BertForMaskedLM.from_pretrained(out).eval()
    ids, mask = _inputs(128)
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids)).logits.numpy()
        b = m2(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)


def test_albert_mlm_parity(tmp_path):
    """ALBERT factorized-embedding MLM head (dense hidden→embedding_size,
    tied decoder); weights perturbed so dropped params can't hide."""
    torch.manual_seed(9)
    cfg = transformers.AlbertConfig(
        vocab_size=128, hidden_size=32, embedding_size=16,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, classifier_dropout_prob=0.0)
    m = transformers.AlbertForMaskedLM(cfg).eval()
    with torch.no_grad():
        for p in m.parameters():
            p.add_(torch.randn_like(p) * 0.02)
    d = str(tmp_path / "albert")
    m.save_pretrained(d)
    model, params, fam, _ = auto_models.from_pretrained(d, task="mlm")
    assert fam == "albert"
    ids, mask = _inputs(128)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


def test_mlm_export_reloads_for_seq_cls(tmp_path):
    """The reference's main path: a pretrained (here: MLM-exported)
    checkpoint loads for sequence classification with pooler +
    classifier freshly initialized (HF from_pretrained semantics) and
    the backbone weights carried over."""
    import numpy as np

    from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForMaskedLM,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig

    cfg = EncoderConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, intermediate_size=64,
                        max_position_embeddings=16, use_pooler=False)
    mlm = BertForMaskedLM(cfg)
    params = init_params(mlm, cfg)
    out = str(tmp_path / "mlm-export")
    auto_models.save_pretrained(out, params, "bert", cfg)

    model, loaded, fam, lcfg = auto_models.from_pretrained(
        out, task="seq-cls", num_labels=2)
    assert fam == "bert" and lcfg.use_pooler
    np.testing.assert_allclose(
        np.asarray(loaded["backbone"]["encoder"]["layer_0"]["attention"]
                   ["query"]["kernel"]),
        np.asarray(params["backbone"]["encoder"]["layer_0"]["attention"]
                   ["query"]["kernel"]), atol=1e-6)
    assert "pooler" in loaded["backbone"] and "classifier" in loaded
