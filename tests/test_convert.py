"""Forward/reverse name-table consistency: params → HF layout → params
must be the identity for every family and head, with no silently dropped
tensors (SURVEY.md §7 hard-part 1)."""

import numpy as np
import jax
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import build_model, init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import bert_config_from_hf
from huggingface_sagemaker_tensorflow_distributed_tpu.models.convert import (
    hf_to_params,
    merge_into,
    params_to_hf,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.distilbert import (
    distilbert_config_from_hf,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.roberta import (
    roberta_config_from_hf,
)

_HF_CFGS = {
    "bert": (bert_config_from_hf, {
        "vocab_size": 64, "hidden_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 2, "intermediate_size": 32,
        "max_position_embeddings": 32}),
    "roberta": (roberta_config_from_hf, {
        "vocab_size": 64, "hidden_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 2, "intermediate_size": 32,
        "max_position_embeddings": 34, "pad_token_id": 1}),
    "distilbert": (distilbert_config_from_hf, {
        "vocab_size": 64, "dim": 16, "n_layers": 2, "n_heads": 2,
        "hidden_dim": 32, "max_position_embeddings": 32}),
}


def _count_leaves(tree):
    return len(jax.tree.leaves(tree))


@pytest.mark.parametrize("family", ["bert", "roberta", "distilbert"])
@pytest.mark.parametrize("task", ["seq-cls", "token-cls", "qa"])
def test_roundtrip_identity(family, task):
    builder, hf_cfg = _HF_CFGS[family]
    overrides = {}
    if family == "bert" and task != "seq-cls":
        overrides["use_pooler"] = False
    config = builder(hf_cfg, **overrides)
    model = build_model(family, task, config, num_labels=3)
    params = init_params(model, config, seed=0)

    state = params_to_hf(params, family)
    # every leaf must survive the forward translation
    assert len(state) == _count_leaves(params), (
        f"{family}/{task}: {_count_leaves(params)} params but "
        f"{len(state)} exported tensors — a reverse rule is missing")

    back = hf_to_params(state, family)
    merged, missing = merge_into(params, back)
    assert missing == [], f"{family}/{task}: unmapped on re-import: {missing}"
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
