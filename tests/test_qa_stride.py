"""QA doc-stride windowing (HF run_qa semantics) across all three
tokenizer tiers, plus the best-window aggregation.

The reference's data path truncates everything to 512 (reference
``scripts/train.py:81``); with ``doc_stride > 0`` long contexts become
overlapping windows so an answer past the truncation boundary is still
trainable and findable — each feature carries ``example_ids`` back to
its input, and eval keeps the highest-scoring span per example
(``utils/metrics.py::best_windowed_answers``).
"""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
    best_windowed_answers,
    extract_answer_spans,
)

L = 32          # feature length: small enough to force windows


def _long_ctx(n_words=100):
    words = [f"w{i}" for i in range(n_words)]
    words[77] = "needle"
    ctx = " ".join(words)
    return ctx, ctx.index("needle"), "needle"


def _check_stride_encoding(tok, token_type=True):
    """Shared contract: truncation loses the deep answer, striding finds
    it; offsets decode it; example_ids map features to inputs."""
    ctx, a_start, answer = _long_ctx()
    q = ["which word"]

    trunc = tok.encode_qa(q, [ctx], [a_start], [answer], max_length=L)
    assert int(trunc["start_positions"][0]) == 0       # truncated away
    assert trunc["input_ids"].shape[0] == 1

    enc = tok.encode_qa(q, [ctx], [a_start], [answer], max_length=L,
                        return_offsets=True, doc_stride=8)
    n_feat = enc["input_ids"].shape[0]
    assert n_feat > 1
    assert np.all(enc["example_ids"] == 0)
    labeled = np.flatnonzero(enc["start_positions"] > 0)
    assert len(labeled) >= 1                           # some window has it
    for r in labeled:
        s = int(enc["start_positions"][r])
        e = int(enc["end_positions"][r])
        assert ctx[enc["offset_starts"][r][s]:
                   enc["offset_ends"][r][e]] == answer
    # every context token is covered by at least one window: the union
    # of char offsets across features spans the whole context
    covered = set()
    for r in range(n_feat):
        for s, e in zip(enc["offset_starts"][r], enc["offset_ends"][r]):
            if s >= 0:
                covered.add((int(s), int(e)))
    n_ctx_tokens = len(ctx.split())
    assert len(covered) == n_ctx_tokens
    return enc


def test_wordhash_doc_stride():
    _check_stride_encoding(WordHashTokenizer(vocab_size=512))


def test_wordpiece_doc_stride():
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.wordpiece import (
        WordPieceTokenizer,
    )

    vocab = {w: i for i, w in enumerate(
        ["[PAD]", "[CLS]", "[SEP]", "[UNK]", "[MASK]", "which", "word",
         "needle"] + [f"w{i}" for i in range(100)])}
    _check_stride_encoding(WordPieceTokenizer(vocab))


def test_hf_tokenizer_doc_stride(tmp_path):
    transformers = pytest.importorskip("transformers")
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        HFTokenizer,
    )

    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "which", "word",
         "needle"] + [f"w{i}" for i in range(100)]) + "\n")
    tok = HFTokenizer(transformers.BertTokenizerFast(
        vocab_file=str(vocab_path), do_lower_case=True))
    _check_stride_encoding(tok)


def test_multi_example_ids_roundtrip():
    """Two inputs of very different lengths: example_ids partitions the
    features correctly and short contexts still get exactly one."""
    tok = WordHashTokenizer(vocab_size=512)
    long_ctx, a_start, answer = _long_ctx()
    enc = tok.encode_qa(["q one", "q two"], [long_ctx, "tiny context"],
                        [a_start, 0], [answer, "tiny"], max_length=L,
                        doc_stride=8)
    ex = enc["example_ids"]
    assert np.sum(ex == 0) > 1 and np.sum(ex == 1) == 1
    # the short example's answer survives at its usual position
    short_row = int(np.flatnonzero(ex == 1)[0])
    assert int(enc["start_positions"][short_row]) > 0


def test_best_windowed_answers_picks_max_score():
    texts = ["", "alpha", "beta", "gamma"]
    scores = [float("-inf"), 1.0, 3.0, 2.0]
    ex_ids = [0, 0, 0, 1]
    assert best_windowed_answers(texts, scores, ex_ids, 2) == ["beta",
                                                               "gamma"]
    # an example whose windows all decode no-answer stays ""
    assert best_windowed_answers([""], [float("-inf")], [0], 1) == [""]


def test_extract_answer_spans_with_scores():
    # 1 row, 3 context tokens at positions 2..4 with char offsets
    s_log = np.array([[0.0, 0.0, 5.0, 0.0, 0.0]])
    e_log = np.array([[0.0, 0.0, 0.0, 4.0, 0.0]])
    off_s = np.array([[-1, -1, 0, 4, 9]])
    off_e = np.array([[-1, -1, 3, 8, 12]])
    ctx = ["abc defg hij"]
    (text, score), = extract_answer_spans(s_log, e_log, off_s, off_e, ctx,
                                          with_scores=True)
    assert text == "abc defg" and score == pytest.approx(9.0)
    (text2, s_tok, e_tok, score2), = extract_answer_spans(
        s_log, e_log, off_s, off_e, ctx, with_spans=True, with_scores=True)
    assert (text2, s_tok, e_tok) == ("abc defg", 2, 3)
    assert score2 == pytest.approx(9.0)


def test_doc_stride_is_overlap_and_clamps():
    """doc_stride is the OVERLAP between windows (the HF fast-tokenizer
    meaning): consecutive windows share exactly `stride` tokens; a
    stride >= the window size clamps to step 1 and coverage never gaps."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        _qa_windows,
    )

    wins = list(_qa_windows(2, 100, 32, 8))   # room = 27, step = 19
    assert wins[0] == (0, 27) and wins[1][0] == 19
    # consecutive windows overlap by exactly doc_stride tokens
    assert wins[0][0] + wins[0][1] - wins[1][0] == 8
    # full coverage, no gaps
    covered = set()
    for w0, nw in wins:
        covered.update(range(w0, w0 + nw))
    assert covered == set(range(100))

    # stride >= room: step clamps to 1 instead of gapping/looping
    wins = list(_qa_windows(2, 40, 32, 64))
    covered = set()
    for w0, nw in wins:
        assert nw > 0
        covered.update(range(w0, w0 + nw))
    assert covered == set(range(40))


def test_window_cutting_answer_head_is_unlabeled():
    """A window that begins mid-answer must label CLS, not the answer's
    tail (HF run_qa full-containment convention, both sides)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.tokenization import (
        _qa_feature,
    )

    # answer = chars 10..25, three tokens; window holds only the last two
    win_spans = [(15, 20), (21, 25), (26, 30)]
    row = _qa_feature(0, [7, 7], win_spans=win_spans,
                      win_ids=[5, 5, 5], max_length=32, labeled=True,
                      a_start=10, a_end=25, cls_id=1, sep_id=2)
    assert row["tok_start"] == row["tok_end"] == 0
    # same window with the head INCLUDED is labeled
    row2 = _qa_feature(0, [7, 7], win_ids=[5, 5, 5, 5],
                       win_spans=[(10, 14)] + win_spans, max_length=32,
                       labeled=True, a_start=10, a_end=25, cls_id=1,
                       sep_id=2)
    # three tokens cover chars 10..25 → positions 4..6 after [CLS] q q [SEP]
    assert row2["tok_start"] == 4 and row2["tok_end"] == 6
